"""Unit tests for TiledMatrix core (reference unit_test/test_Tile.cc,
test_Matrix.cc analogues)."""

import jax.numpy as jnp
import numpy as np
import pytest

import slate_tpu as st
from slate_tpu import (Diag, MatrixType, Op, TiledMatrix, Uplo)


def test_from_dense_roundtrip(rng):
    a = rng.standard_normal((100, 70))
    A = TiledMatrix.from_dense(a, mb=32, nb=16)
    assert A.m == 100 and A.n == 70
    assert A.data.shape == (128, 80)
    assert A.mt == 4 and A.nt == 5
    np.testing.assert_array_equal(A.to_numpy(), a)
    # padding is zero
    assert np.all(np.asarray(A.data)[100:, :] == 0)
    assert np.all(np.asarray(A.data)[:, 70:] == 0)


def test_tile_sizes(rng):
    A = TiledMatrix.from_dense(rng.standard_normal((100, 70)), 32, 16)
    assert A.tileMb(0) == 32 and A.tileMb(3) == 4
    assert A.tileNb(0) == 16 and A.tileNb(4) == 6


def test_tile_view(rng):
    a = rng.standard_normal((64, 64))
    A = TiledMatrix.from_dense(a, 16)
    np.testing.assert_array_equal(np.asarray(A.tile(1, 2)),
                                  a[16:32, 32:48])


def test_transpose_flag(rng):
    a = rng.standard_normal((40, 20))
    A = TiledMatrix.from_dense(a, 16)
    At = A.transpose()
    assert At.shape == (20, 40)
    assert At.op is Op.Trans
    np.testing.assert_array_equal(At.to_numpy(), a.T)
    np.testing.assert_array_equal(At.transpose().to_numpy(), a)


def test_conj_transpose_complex(rng):
    a = rng.standard_normal((24, 12)) + 1j * rng.standard_normal((24, 12))
    A = TiledMatrix.from_dense(a, 8)
    np.testing.assert_array_equal(A.conj_transpose().to_numpy(), a.conj().T)
    # H of H is identity
    np.testing.assert_array_equal(
        A.conj_transpose().conj_transpose().to_numpy(), a)
    # T then H composes to conj
    np.testing.assert_allclose(
        A.transpose().conj_transpose().to_numpy(), a.conj())


def test_sub(rng):
    a = rng.standard_normal((64, 64))
    A = TiledMatrix.from_dense(a, 16)
    S = A.sub(1, 2, 0, 1)
    assert S.m == 32 and S.n == 32
    np.testing.assert_array_equal(S.to_numpy(), a[16:48, 0:32])
    # ragged sub at the edge
    B = TiledMatrix.from_dense(a[:50, :50], 16)
    S = B.sub(2, 3, 2, 3)
    assert S.m == 18 and S.n == 18
    np.testing.assert_array_equal(S.to_numpy(), a[32:50, 32:50])


def test_slice(rng):
    a = rng.standard_normal((64, 64))
    A = TiledMatrix.from_dense(a, 16)
    S = A.slice(3, 40, 5, 20)
    np.testing.assert_array_equal(S.to_numpy(), a[3:41, 5:21])


def test_symmetric_to_dense(rng):
    a = rng.standard_normal((30, 30))
    S = st.SymmetricMatrix(Uplo.Lower, a, mb=8)
    full = S.to_numpy()
    np.testing.assert_array_equal(full, np.tril(a) + np.tril(a, -1).T)
    U = st.SymmetricMatrix(Uplo.Upper, a, mb=8)
    np.testing.assert_array_equal(U.to_numpy(),
                                  np.triu(a) + np.triu(a, 1).T)


def test_hermitian_to_dense(rng):
    a = rng.standard_normal((20, 20)) + 1j * rng.standard_normal((20, 20))
    H = st.HermitianMatrix(Uplo.Lower, a, mb=8)
    full = H.to_numpy()
    np.testing.assert_allclose(full, full.conj().T)
    np.testing.assert_array_equal(np.tril(full, -1), np.tril(a, -1))
    np.testing.assert_array_equal(np.diagonal(full), np.real(np.diagonal(a)))


def test_triangular_to_dense(rng):
    a = rng.standard_normal((20, 20))
    L = st.TriangularMatrix(Uplo.Lower, a, mb=8)
    np.testing.assert_array_equal(L.to_numpy(), np.tril(a))
    Lu = st.TriangularMatrix(Uplo.Lower, a, mb=8, diag=Diag.Unit)
    exp = np.tril(a, -1) + np.eye(20)
    np.testing.assert_array_equal(Lu.to_numpy(), exp)


def test_triangular_transpose_flips_uplo(rng):
    a = rng.standard_normal((20, 20))
    L = st.TriangularMatrix(Uplo.Lower, a, mb=8)
    Lt = L.transpose().resolve()
    assert Lt.uplo is Uplo.Upper
    np.testing.assert_array_equal(Lt.to_numpy(), np.tril(a).T)


def test_band_to_dense(rng):
    a = rng.standard_normal((16, 16))
    B = st.BandMatrix(2, 1, a, mb=8)
    full = B.to_numpy()
    np.testing.assert_array_equal(full, np.triu(np.tril(a, 1), -2))


def test_pytree(rng):
    import jax
    a = rng.standard_normal((32, 16))
    A = TiledMatrix.from_dense(a, 16)
    leaves, treedef = jax.tree_util.tree_flatten(A)
    assert len(leaves) == 1
    A2 = jax.tree_util.tree_unflatten(treedef, leaves)
    assert A2.m == A.m and A2.mtype == A.mtype
    # jit through the pytree
    f = jax.jit(lambda M: M.data.sum())
    f(A)


def test_square_validation(rng):
    with pytest.raises(st.DimensionError):
        st.SymmetricMatrix(Uplo.Lower, rng.standard_normal((4, 6)), mb=4)


def test_empty_like(rng):
    A = TiledMatrix.from_dense(rng.standard_normal((30, 20)), 16)
    E = A.emptyLike()
    assert E.m == 30 and E.n == 20 and E.dtype == A.dtype
    assert np.all(E.to_numpy() == 0)


def test_zero_size():
    A = TiledMatrix.zeros(0, 0, 16)
    assert A.m == 0 and A.n == 0
    assert A.to_numpy().shape == (0, 0)


def test_grid_funcs():
    from slate_tpu.core.func import (is_2d_cyclic_grid, process_2d_grid,
                                     uniform_blocksize)
    from slate_tpu import GridOrder
    f = process_2d_grid(GridOrder.Col, 2, 3)
    assert f((0, 0)) == 0 and f((1, 0)) == 1 and f((2, 0)) == 0
    assert f((0, 1)) == 2 and f((1, 2)) == 5
    ok, order, p, q = is_2d_cyclic_grid(6, 6, f)
    assert ok and p == 2 and q == 3 and order == GridOrder.Col
    sz = uniform_blocksize(100, 32)
    assert sz(0) == 32 and sz(3) == 4


def test_make_grid():
    import jax
    g = st.make_grid(2, 4)
    assert g.p == 2 and g.q == 4
    assert g.nprocs == 8
    # sharding applies
    A = TiledMatrix.from_dense(np.ones((64, 64)), 16)
    d = jax.device_put(A.data, g.matrix_sharding())
    assert len(d.sharding.device_set) == 8


def test_sub_on_transposed_view(rng):
    # reference sub() works through the op flag (BaseMatrix.hh:104);
    # round-1 asserted NoTrans — now it resolves transparently
    a = rng.standard_normal((32, 48))
    A = TiledMatrix.from_dense(a, 8)
    S = A.transpose().sub(1, 2, 0, 1)     # tiles of a.T
    np.testing.assert_array_equal(S.to_numpy(), a.T[8:24, 0:16])


def test_non_uniform_tiles_basic(rng):
    """Per-index tile sizes (reference BaseMatrix.hh:80-101 lambdas):
    construction from explicit sizes and from a TileSizeFunc, tile
    indexing, to_dense round-trip."""
    from slate_tpu.core.func import uniform_blocksize

    a = rng.standard_normal((20, 14))
    A = TiledMatrix.from_func(a, [4, 10, 6], [8, 6])
    assert (A.mt, A.nt) == (3, 2)
    assert [A.tileMb(i) for i in range(3)] == [4, 10, 6]
    assert [A.tileNb(j) for j in range(2)] == [8, 6]
    np.testing.assert_array_equal(np.asarray(A.tile(1, 1)), a[4:14, 8:14])
    np.testing.assert_array_equal(A.to_numpy(), a)

    B = TiledMatrix.from_func(a, uniform_blocksize(20, 6),
                              uniform_blocksize(14, 6))
    assert [B.tileMb(i) for i in range(B.mt)] == [6, 6, 6, 2]
    assert [B.tileNb(j) for j in range(B.nt)] == [6, 6, 2]


def test_non_uniform_sub_transpose_uniform(rng):
    a = rng.standard_normal((18, 18))
    A = TiledMatrix.from_func(a, [6, 4, 8])
    # sub keeps and re-bases boundaries
    S = A.sub(1, 2, 0, 1)
    np.testing.assert_array_equal(S.to_numpy(), a[6:18, 0:10])
    assert [S.tileMb(i) for i in range(S.mt)] == [4, 8]
    assert [S.tileNb(j) for j in range(S.nt)] == [6, 4]
    # transpose swaps boundaries
    T = A.transpose().resolve()
    assert [T.tileMb(i) for i in range(T.mt)] == [6, 4, 8]
    np.testing.assert_array_equal(T.to_numpy(), a.T)
    # uniform() re-tiles to the padded layout
    U = A.uniform()
    assert U.rb is None and U.cb is None
    np.testing.assert_array_equal(U.to_numpy(), a)


def test_non_uniform_gemm_and_factor(rng):
    """gemm as first consumer + factorization entry auto-retile."""
    n = 24
    a = rng.standard_normal((n, n))
    b = rng.standard_normal((n, n))
    sizes = [4, 8, 8, 4]
    A = TiledMatrix.from_func(a, sizes)
    B = TiledMatrix.from_func(b, sizes)
    C0 = TiledMatrix.from_func(np.zeros((n, n)), sizes)
    C = st.gemm(1.0, A, B, 0.0, C0)
    np.testing.assert_allclose(C.to_numpy(), a @ b, atol=1e-10)
    # factorization drivers accept non-uniform input (retile at entry)
    spd = a @ a.T / n + 4 * np.eye(n)
    H = TiledMatrix.from_func(spd, sizes)
    import dataclasses as dc
    from slate_tpu.core.enums import MatrixType
    H = dc.replace(H, mtype=MatrixType.Hermitian, uplo=Uplo.Lower)
    L = st.potrf(H)
    Ld = np.tril(L.to_numpy())
    np.testing.assert_allclose(Ld @ Ld.T, spd, atol=1e-8)
