"""ISSUE 9 acceptance: kill a worker mid shard_potrf_ooc on a REAL
2-process mesh, assert the parent surfaces a structured WorkerLost
within the deadline (not the old silent hang), then resume from the
per-host checkpoints to a factor BITWISE equal to the uninterrupted
single-engine stream's."""
import json
from pathlib import Path

import pytest

from slate_tpu.resil import faults
from slate_tpu.resil.guard import WorkerLost
from slate_tpu.testing import multiproc as mp

WORKER = Path(__file__).with_name("resil_worker.py")


@pytest.mark.slow
@pytest.mark.parametrize("lookahead", [0, 1])
def test_two_process_kill_resume(tmp_path, lookahead):
    """lookahead=1 (ISSUE 11) kills the worker with TWO panels in
    flight — the step-3 fault fires inside step 2's lookahead
    prologue — and the min-epoch resume must still land bitwise."""
    ck = tmp_path / "ck"
    ck.mkdir()

    # -- phase 1: worker 1 dies at step 3 (a planned `kill` rule
    # scoped to host 1); worker 0 wedges in the next broadcast and
    # the parent must reap BOTH with diagnostics inside the deadline
    plan = faults.FaultPlan([
        {"site": "step",
         "match": {"op": "shard_potrf_ooc", "step": 3, "host": 1},
         "times": 1, "kind": "kill"}])
    with pytest.raises(WorkerLost) as ei:
        mp.launch(str(WORKER), num_processes=2,
                  extra_args=["crash", str(ck), str(lookahead)],
                  env=faults.install_env_var(plan),
                  timeout=300, death_grace=10.0)
    e = ei.value
    assert e.process_id == 1
    assert e.returncode == faults.KILL_EXIT_CODE
    assert len(e.outs) == 2

    # both hosts committed panels before the kill (ckpt_every=1,
    # killed at the step-3 gate => epoch 3 durable on each)
    epochs = {}
    for host in (0, 1):
        meta = json.loads(
            (ck / ("host%d" % host) / "meta.json").read_text())
        epochs[host] = meta["epoch"]
        assert meta["driver"] == "shard_potrf_ooc"
    assert min(epochs.values()) >= 1, epochs

    # -- phase 2: same checkpoint dir, no fault plan — the mesh
    # agrees on the min epoch, resumes, and every host's factor is
    # BITWISE the uninterrupted single-engine stream's
    procs, outs = mp.launch(str(WORKER), num_processes=2,
                            extra_args=["resume", str(ck),
                                        str(lookahead)],
                            timeout=300)
    mp.assert_success(procs, outs)
    recs = [mp.results(out) for out in outs]
    shas = set()
    for pid, r in enumerate(recs):
        rec = r["potrf"]
        assert rec["mode"] == "resume"
        assert rec["bitwise_vs_stream"], \
            "proc %d resumed factor != stream" % pid
        shas.add(rec["sha"])
    assert len(shas) == 1       # both hosts hold the same factor
