"""Worker for the resilience multi-process test (ISSUE 9): one of two
processes on the global 2x4 virtual-CPU mesh running shard_potrf_ooc
with per-host checkpointing.

Run as  python tests/resil_worker.py <pid> <port> <mode> <ckpt_dir>
[lookahead]

``lookahead`` (ISSUE 11, default 0): the broadcast-pipeline depth —
at 1 the kill fires with two panels in flight (the step fault site
fires per lookahead slot) and the resume must still land bitwise on
the single-engine stream's factor.

``mode``:

  * ``crash``  — checkpointing on; the parent ships a fault plan via
    ``SLATE_RESIL_FAULTS`` (installed by multiproc.init) that KILLS
    host 1 at an injected step — this invocation never emits;
  * ``resume`` — same checkpoint dir, no plan: the mesh agrees on the
    min committed epoch, resumes, and emits the factor's sha256 plus
    a bitwise pin against the local single-engine stream (stream ==
    uninterrupted shard == resumed shard, so the pin IS the
    crash/resume acceptance criterion).
"""
import hashlib
import pathlib
import sys

sys.path.insert(0, str(pathlib.Path(__file__).resolve().parents[1]))
from slate_tpu.testing import multiproc as mp  # noqa: E402

pid, port, mode, ckdir = (int(sys.argv[1]), sys.argv[2], sys.argv[3],
                          sys.argv[4])
lookahead = int(sys.argv[5]) if len(sys.argv) > 5 else 0
grid, _ = mp.startup(pid, port, num_processes=2, expect_devices=8)

import numpy as np  # noqa: E402

from slate_tpu.dist import shard_ooc  # noqa: E402
from slate_tpu.linalg import ooc  # noqa: E402

n, w = 160, 32
rng = np.random.default_rng(0)
x = rng.standard_normal((n, n)).astype(np.float32)
a = x @ x.T / n + 4.0 * np.eye(n, dtype=np.float32)

L = shard_ooc.shard_potrf_ooc(a, grid, panel_cols=w,
                              cache_budget_bytes=0,
                              lookahead=lookahead,
                              ckpt_path=ckdir, ckpt_every=1)
# only reached when no kill fired (mode == "resume", or a crash run
# that failed to crash — the parent asserts on which)
L0 = ooc.potrf_ooc(a, panel_cols=w, cache_budget_bytes=0)
mp.emit("potrf", proc=pid, mode=mode,
        sha=hashlib.sha256(
            np.ascontiguousarray(np.asarray(L)).tobytes()).hexdigest(),
        bitwise_vs_stream=bool(np.array_equal(np.asarray(L), L0)))
