"""Ragged batched Pallas kernels + the batch "ragged" strategy
(ISSUE 15): per-element sizes-masked potrf/getrf/trsm executing under
the Pallas interpreter, the bucket-dimension-free coalescing route,
the cold-route bucket pin, and the obs/stats surfaces."""

import numpy as np
import pytest
import scipy.linalg as sla

import jax.numpy as jnp

from slate_tpu import batch
from slate_tpu.batch import bucket
from slate_tpu.core.methods import MethodBatchStrategy
from slate_tpu.ops import pallas_kernels as pk


def _spd(rng, n):
    x = rng.standard_normal((n, n))
    return x @ x.T + n * np.eye(n)


def _stack_garbage(mats, ceil):
    """Stack to the ceiling with GARBAGE in the pad region — the
    kernels rebuild validity-masked padding in-kernel, so nothing the
    stacker leaves there may leak into any element's answer."""
    out = np.zeros((len(mats), ceil, ceil), np.asarray(mats[0]).dtype)
    for i, a in enumerate(mats):
        s = a.shape[0]
        out[i, s:, :] = 7.25
        out[i, :, s:] = -3.5
        out[i, :s, :s] = a
    return out


# -- kernel level ---------------------------------------------------------

def test_ragged_potrf_kernel_adversarial(rng):
    """Heterogeneous orders including size-1 and ceiling-size
    elements, garbage in the pad region: every [:s, :s] crop must
    match the per-element unbatched factor at f64 precision, and the
    pad region must come back as the identity's lower triangle."""
    sizes = [1, 33, 70, 96]
    mats = [_spd(rng, s) for s in sizes]
    ceil = 96
    stack = _stack_garbage(mats, ceil)
    out = pk.ragged_potrf(jnp.asarray(stack), np.asarray(sizes))
    assert out is not None
    out = np.asarray(out)
    for i, s in enumerate(sizes):
        ref = np.linalg.cholesky(mats[i])
        np.testing.assert_allclose(out[i, :s, :s], ref, rtol=1e-12,
                                   atol=1e-12)
        # validity-masked padding, enforced in-kernel: identity diag,
        # exact zeros off it (the blkdiag(L, I) contract)
        assert np.array_equal(out[i, s:, :s], np.zeros((ceil - s, s)))
        assert np.array_equal(np.diag(out[i])[s:],
                              np.ones(ceil - s))


def test_ragged_getrf_kernel_pivots_match_fori(rng):
    """The masked-pivoting discipline: pivot swap targets must equal
    the per-element lu_panel_fori sequence EXACTLY on an adversarial
    batch — cross-element pivoting (each element permuted
    differently), a rank-deficient element (zero column), size-1 and
    ceiling-size elements, exact ties — with padded columns pivoting
    on their own unit diagonal (identity swaps, so padded rows stay
    unpivotable)."""
    from slate_tpu.linalg.lu import lu_panel_fori
    ceil = 64
    mats = []
    a = rng.standard_normal((40, 40))
    mats.append(a[rng.permutation(40)])            # cross-element piv
    b = rng.standard_normal((33, 33))
    b[:, 7] = 0.0                                  # rank-deficient
    mats.append(b)
    mats.append(np.array([[3.5]]))                 # size-1
    c = rng.standard_normal((ceil, ceil))
    c[5] = c[11]                                   # exact tie rows
    mats.append(c[rng.permutation(ceil)])          # ceiling-size
    sizes = [m.shape[0] for m in mats]
    stack = _stack_garbage(mats, ceil)
    got = pk.ragged_getrf(jnp.asarray(stack), np.asarray(sizes))
    assert got is not None
    lu, piv = np.asarray(got[0]), np.asarray(got[1])
    for i, (a, s) in enumerate(zip(mats, sizes)):
        ref_lu, ref_piv = lu_panel_fori(jnp.asarray(a))
        np.testing.assert_array_equal(piv[i, :s], np.asarray(ref_piv))
        np.testing.assert_allclose(lu[i, :s, :s], np.asarray(ref_lu),
                                   rtol=1e-11, atol=1e-11)
        # live pivots stay inside the live rows; padded columns are
        # identity swaps
        assert piv[i, :s].max() < s
        np.testing.assert_array_equal(piv[i, s:],
                                      np.arange(s, ceil))


def test_ragged_getrf_matches_scipy(rng):
    sizes = [24, 64, 50]
    mats = [rng.standard_normal((s, s)) + 0.1 * s * np.eye(s)
            for s in sizes]
    stack = _stack_garbage(mats, 64)
    lu, piv = pk.ragged_getrf(jnp.asarray(stack), np.asarray(sizes))
    for i, (a, s) in enumerate(zip(mats, sizes)):
        ref_lu, ref_piv = sla.lu_factor(a)
        np.testing.assert_allclose(np.asarray(lu)[i, :s, :s], ref_lu,
                                   rtol=1e-9, atol=1e-10)
        np.testing.assert_array_equal(np.asarray(piv)[i, :s], ref_piv)


@pytest.mark.parametrize("upper,trans,unit", [
    (False, False, False),     # posv forward sweep
    (False, True, False),      # posv backward sweep (L^T)
    (True, False, False),      # gesv U back-solve
    (False, False, True),      # gesv unit-L forward sweep
])
def test_ragged_trsm_modes(rng, upper, trans, unit):
    """Every solve mode the ragged posv/gesv compositions use, per
    element vs scipy.solve_triangular; padded rhs rows come back
    exact zeros."""
    sizes = [17, 64, 40]
    ceil, k = 64, 3
    tris, rhss = [], []
    for s in sizes:
        t = rng.standard_normal((s, s)) + 3.0 * s * np.eye(s)
        tris.append(np.tril(t) if not upper else np.triu(t))
        rhss.append(rng.standard_normal((s, k)))
    packed = _stack_garbage(tris, ceil)
    rhs = np.zeros((len(sizes), ceil, k))
    for i, b in enumerate(rhss):
        rhs[i, : b.shape[0]] = b
        rhs[i, b.shape[0]:] = 11.0        # garbage pad rows
    out = pk.ragged_trsm(jnp.asarray(packed), jnp.asarray(rhs),
                         np.asarray(sizes), upper=upper, trans=trans,
                         unit=unit)
    assert out is not None
    out = np.asarray(out)
    for i, (t, b, s) in enumerate(zip(tris, rhss, sizes)):
        ref = sla.solve_triangular(
            t, b, lower=not upper, trans=1 if trans else 0,
            unit_diagonal=unit)
        np.testing.assert_allclose(out[i, :s], ref, rtol=1e-10,
                                   atol=1e-10)
        assert np.array_equal(out[i, s:], np.zeros((ceil - s, k)))


def test_ragged_kernel_eligibility_gates():
    # misaligned ceiling / unsupported dtype reject (None) instead of
    # computing — the caller keeps the bucket strategy
    assert pk.ragged_potrf_eligible(64, np.float64)
    assert not pk.ragged_potrf_eligible(65, np.float64)
    assert not pk.ragged_potrf_eligible(64, np.complex128)
    assert not pk.ragged_trsm_eligible(64, 0, np.float64)
    assert pk.ragged_trsm_eligible(64, 1, np.float64)
    bad = jnp.zeros((2, 40, 40))       # 40 % blk(32) != 0
    assert pk.ragged_potrf(bad, np.array([40, 40])) is None
    assert pk.ragged_getrf(bad, np.array([40, 40])) is None


# -- ragged ceiling / report math ----------------------------------------

def test_ragged_ceiling_and_report():
    # ceiling: max live size rounded to lcm(align=8, blk=32) = 32
    assert bucket.ragged_ceiling([70, 24], blk=32) == 96
    assert bucket.ragged_ceiling([1], blk=32) == 32
    assert bucket.ragged_ceiling([96], blk=32) == 96
    with pytest.raises(ValueError):
        bucket.ragged_ceiling([], blk=32)
    rep = bucket.ragged_report([70, 32], 32)
    assert rep["occupancy"] == 2
    ext3 = 96 ** 3 + 32 ** 3
    assert rep["padding_waste_flops"] == pytest.approx(
        1 - (70 ** 3 + 32 ** 3) / ext3)
    assert rep["scheduled_flops"] == pytest.approx(ext3)
    # flops saved vs the pow2 bucket route: 70 -> 128, 32 -> 64
    assert rep["flops_saved"] == pytest.approx(
        (128 ** 3 - 96 ** 3) + (64 ** 3 - 32 ** 3))
    # block-aligned exact sizes waste nothing
    assert bucket.ragged_report([64, 32], 32)[
        "padding_waste_flops"] == 0.0


# -- queue strategy routing ----------------------------------------------

def test_queue_ragged_coalesces_across_buckets(rng):
    """Sizes spanning pow2 buckets 64 and 128 merge into ONE ragged
    dispatch (the coalescing key drops the bucket dimension) at a
    tighter ceiling, with less cubic padding than the bucket
    strategy, at equal (allclose) results."""
    sizes = [24, 40, 70]
    spds = [_spd(rng, s) for s in sizes]
    with batch.CoalescingQueue(max_wait_us=0,
                               strategy="ragged") as qr:
        tickets = [qr.submit("potrf", a) for a in spds]
        qr.flush()
        rag = [t.result() for t in tickets]
    sr = qr.stats()
    with batch.CoalescingQueue(max_wait_us=0,
                               strategy="bucket") as qb:
        tickets = [qb.submit("potrf", a) for a in spds]
        qb.flush()
        buc = [t.result() for t in tickets]
    sb = qb.stats()
    assert sr["dispatches"] == 1           # one ragged dispatch...
    assert sr["ragged_dispatches"] == 1
    assert sb["dispatches"] == 2           # ...vs two pow2 buckets
    assert sb["ragged_dispatches"] == 0
    assert sr["mean_padding_waste_flops"] \
        < sb["mean_padding_waste_flops"]
    assert sr["ragged_flops_saved"] > 0
    for a, r, b in zip(spds, rag, buc):
        ref = np.linalg.cholesky(a)
        np.testing.assert_allclose(r, ref, rtol=1e-10, atol=1e-10)
        np.testing.assert_allclose(r, b, rtol=1e-10, atol=1e-10)


def test_queue_ragged_solves_heterogeneous(rng):
    """posv/gesv through the ragged route: heterogeneous orders,
    multi-column rhs, answers allclose to per-element references."""
    sizes = [9, 33, 64]
    spds = [_spd(rng, s) for s in sizes]
    gens = [rng.standard_normal((s, s)) + 0.1 * s * np.eye(s)
            for s in sizes]
    rhss = [rng.standard_normal((s, 2)) for s in sizes]
    for op, mats in (("posv", spds), ("gesv", gens)):
        outs = batch.run(op, mats, rhs=rhss, strategy="ragged")
        for x, a, b in zip(outs, mats, rhss):
            np.testing.assert_allclose(a @ np.asarray(x), b,
                                       rtol=1e-8, atol=1e-8)


def test_queue_ragged_getrf_roundtrip(rng):
    sizes = [12, 40]
    mats = [rng.standard_normal((s, s)) + s * np.eye(s)
            for s in sizes]
    outs = batch.run("getrf", mats, strategy="ragged")
    for (lu, piv), a in zip(outs, mats):
        ref_lu, ref_piv = sla.lu_factor(a)
        np.testing.assert_allclose(lu, ref_lu, rtol=1e-9, atol=1e-10)
        np.testing.assert_array_equal(piv, ref_piv)


def test_cold_route_is_bucket_bitwise(rng):
    """The FROZEN ``batch/strategy`` row is "bucket": a cold tune
    cache must coalesce exactly as PR 5 — same per-bucket dispatch
    count, bit-identical results to an explicit bucket queue."""
    q = batch.CoalescingQueue()
    assert q._strategy is MethodBatchStrategy.Bucket
    q.close()
    sizes = [24, 70]
    spds = [_spd(rng, s) for s in sizes]
    cold = batch.run("potrf", spds)
    explicit = batch.run("potrf", spds, strategy="bucket")
    for a, b in zip(cold, explicit):
        assert np.array_equal(np.asarray(a), np.asarray(b))


def test_tuned_strategy_routes_ragged(tmp_path, monkeypatch, rng):
    """An earned ``batch/strategy``="ragged" cache entry flips the
    queue's Auto route (no code/kwarg change); an unknown value from
    a newer cache demotes to Bucket, never an error."""
    from slate_tpu.tune import cache as tc
    monkeypatch.setenv("SLATE_TPU_TUNE_CACHE", str(tmp_path))
    tc.reset_cache()
    try:
        tc.get_cache().put("batch", None, None,
                           {"strategy": "ragged"})
        q = batch.CoalescingQueue()
        assert q._strategy is MethodBatchStrategy.Ragged
        q.close()
        spds = [_spd(rng, s) for s in (10, 33)]
        outs = batch.run("potrf", spds)
        assert all(np.allclose(L, np.linalg.cholesky(a), rtol=1e-10,
                               atol=1e-10)
                   for L, a in zip(outs, spds))
        tc.get_cache().put("batch", None, None,
                           {"strategy": "hexagonal"})
        tc.reset_cache()
        tc.get_cache().put("batch", None, None,
                           {"strategy": "hexagonal"})
        q = batch.CoalescingQueue()
        assert q._strategy is MethodBatchStrategy.Bucket
        q.close()
    finally:
        tc.reset_cache()


def test_ragged_ineligible_dtype_degrades_to_bucket(rng):
    """A dtype the ragged kernels cannot take (complex) keeps the
    bucket path under strategy="ragged" — graceful per-request
    degradation, correct answers, zero ragged dispatches."""
    n = 12
    x = rng.standard_normal((n, n)) + 1j * rng.standard_normal((n, n))
    a = x @ np.conj(x.T) + n * np.eye(n)
    with batch.CoalescingQueue(max_wait_us=0,
                               strategy="ragged") as q:
        t = q.submit("potrf", a)
        q.flush()
        L = t.result()
    assert q.stats()["ragged_dispatches"] == 0
    np.testing.assert_allclose(L @ np.conj(L.T), a, rtol=1e-10,
                               atol=1e-9)


def test_ragged_obs_counters_and_ledger_meta(rng):
    """batch.ragged_dispatches / batch.ragged_flops_saved land in
    obs.snapshot(), and the per-dispatch flight-recorder record
    carries the strategy + ceiling (PR 14 one-shot append)."""
    from slate_tpu import obs
    from slate_tpu.obs import ledger
    from slate_tpu.obs import metrics as om
    spds = [_spd(rng, s) for s in (20, 40)]
    ledger.reset()
    obs.enable()
    ledger.enable()
    try:
        om.reset()
        batch.run("potrf", spds, strategy="ragged")
        c = obs.snapshot()["metrics"]["counters"]
        assert c["batch.ragged_dispatches"] == 1
        assert c["batch.ragged_flops_saved"] > 0
        assert c["batch.dispatches"] == 1
        recs = ledger.records("batch.dispatch")
        assert len(recs) == 1
        assert recs[0].meta["strategy"] == "ragged"
        assert recs[0].meta["ceiling"] == 64
        assert set(recs[0].phases) <= {"stage", "factor"}
    finally:
        ledger.reset()
        obs.disable()
        om.reset()


def test_ragged_zero_column_rhs_degrades_to_bucket(rng):
    """A zero-column rhs is legal on the bucket path (pads to
    (bm, 0)); ragged_trsm needs k >= 1, so the route gate must send
    it to the bucket path instead of failing the ticket at flush."""
    a = _spd(rng, 12)
    with batch.CoalescingQueue(max_wait_us=0,
                               strategy="ragged") as q:
        t = q.submit("posv", a, np.zeros((12, 0)))
        q.flush()
        x = t.result()
    assert x.shape == (12, 0)
    assert q.stats()["ragged_dispatches"] == 0


def test_ragged_submit_snapshots_operands(rng):
    """submit() must capture the operand VALUES (the bucket path
    copies via pad_square at submit): mutating the caller's arrays
    between submit and flush must not change the answer."""
    a = _spd(rng, 20)
    b = rng.standard_normal((20, 2))
    a0, b0 = a.copy(), b.copy()
    with batch.CoalescingQueue(max_wait_us=10 ** 7,
                               strategy="ragged") as q:
        t = q.submit("posv", a, b)
        a[:] = 0.0
        b[:] = 0.0
        q.flush()
        x = t.result()
    np.testing.assert_allclose(a0 @ np.asarray(x), b0, rtol=1e-9,
                               atol=1e-9)


def test_mean_occupancy_weighted(rng):
    """The flops-weighted mean occupancy weights each dispatch by its
    scheduled cubic extent — the occupancy the MXU actually sees
    (ISSUE 15 satellite)."""
    small = [_spd(rng, 10)]                      # bucket 64, occ 1
    big = [_spd(rng, 70), _spd(rng, 100)]        # bucket 128, occ 2
    with batch.CoalescingQueue(max_wait_us=0) as q:
        for a in small:
            q.submit("potrf", a)
        q.flush()
        for a in big:
            q.submit("potrf", a)
        q.flush()
    s = q.stats()
    f1, f2 = 1 * 64.0 ** 3, 2 * 128.0 ** 3
    want = (1 * f1 + 2 * f2) / (f1 + f2)
    assert s["mean_occupancy_weighted"] == pytest.approx(want)
    assert s["mean_occupancy"] == pytest.approx(1.5)
