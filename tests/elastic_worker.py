"""Worker for the elastic-mesh multi-process coverage (ISSUE 19):
one of two processes on the global 2x4 virtual-CPU mesh running
shard_potrf_ooc under an ownership route chosen by ``mode``.

Run as  python tests/elastic_worker.py <pid> <port> <mode> [ckpt_dir]

``mode``:

  * ``uniform``      — elastic route with a UNIFORM installed speed
    vector: the planner's threshold gate must keep the cyclic map
    (zero remaps) and the factor must be bitwise the single-engine
    stream's — the relabel machinery at rest;
  * ``slow_static``  — FROZEN static route under the parent's seeded
    straggler plan (a ``slow`` rule scoped ``{"host": 1, "mine":
    true}``: host 1 stalls on every panel it OWNS) — the baseline
    wall the elastic leg is compared against;
  * ``slow_elastic`` — elastic route under the SAME plan: measured
    throughput (real walls, inflated by the injection) drives the
    remap, panels move off host 1, and the wall must drop while the
    factor stays bitwise;
  * ``crash``        — elastic route with per-host checkpointing; the
    parent's plan KILLS host 1 mid-stream (this invocation never
    emits) and the parent then runs the shrink-to-fit survivor
    resume against the same checkpoint root.

Every completing mode emits wall, the process-wide remap record
mirror, the broadcast-wait counter (the straggler-idle numerator),
the factor sha, and a bitwise pin against the local single-engine
stream.
"""
import hashlib
import pathlib
import sys
import time

sys.path.insert(0, str(pathlib.Path(__file__).resolve().parents[1]))
from slate_tpu.testing import multiproc as mp  # noqa: E402

pid, port, mode = int(sys.argv[1]), sys.argv[2], sys.argv[3]
ckdir = sys.argv[4] if len(sys.argv) > 4 and sys.argv[4] != "-" \
    else None
grid, _ = mp.startup(pid, port, num_processes=2, expect_devices=8)

import numpy as np  # noqa: E402

from slate_tpu import obs  # noqa: E402
from slate_tpu.dist import elastic, shard_ooc  # noqa: E402
from slate_tpu.linalg import ooc  # noqa: E402
from slate_tpu.obs import metrics as om  # noqa: E402

# the slow legs use a longer stream (more panels per host) so the
# remap has not-yet-factored work left to move when it fires
n, w = (160, 32) if mode in ("uniform", "crash") else (384, 32)
rng = np.random.default_rng(0)
x = rng.standard_normal((n, n)).astype(np.float32)
a = x @ x.T / n + 4.0 * np.eye(n, dtype=np.float32)

ownership = "static" if mode == "slow_static" else "elastic"
if mode in ("uniform", "crash"):
    # pin the planner's no-remap branch against CI timing noise —
    # measurement is bypassed, the threshold gate sees a flat fleet
    elastic.install_speeds([1.0] * grid.p * grid.q)

obs.enable()
t0 = time.perf_counter()
L = shard_ooc.shard_potrf_ooc(
    a, grid, panel_cols=w, cache_budget_bytes=0,
    ownership=ownership, ckpt_path=ckdir,
    ckpt_every=1 if ckdir else None)
wall = time.perf_counter() - t0

# only reached when no kill fired (the parent asserts on which)
c = om.snapshot()["counters"]
rr = elastic.remap_records()
L0 = ooc.potrf_ooc(a, panel_cols=w, cache_budget_bytes=0)
mp.emit("elastic", proc=pid, mode=mode, wall_s=round(wall, 4),
        remaps=rr["remaps"], panels_moved=rr["panels_moved"],
        bcast_wait_s=round(
            float(c.get("ooc.shard.bcast_wait_seconds", 0.0)), 4),
        sha=hashlib.sha256(np.ascontiguousarray(
            np.asarray(L)).tobytes()).hexdigest(),
        bitwise_vs_stream=bool(np.array_equal(np.asarray(L), L0)))
