"""Autotuning subsystem tests (ISSUE 1): cache round-trip /
versioning / corrupt-file recovery, frozen-defaults fallback,
selection precedence (explicit > cached > frozen), the bit-identical
cold-start contract, a CPU probe smoke test, and the two polar.py
invariant regressions (dip-region singular value, clustered small
sigmas) that ride in the same PR."""

import json
import os

import jax
import jax.numpy as jnp
import numpy as np
import pytest

import slate_tpu as st
from slate_tpu.core.enums import Diag, MatrixType, Op, Uplo
from slate_tpu.core.options import Option, get_option_tuned
from slate_tpu.core.tiles import TiledMatrix
from slate_tpu.tune import cache as tcache
from slate_tpu.tune import select, stats


@pytest.fixture
def tune_env(tmp_path, monkeypatch):
    """Isolated cache dir + clean counters; never touches ~/.cache."""
    monkeypatch.setenv("SLATE_TPU_TUNE_CACHE", str(tmp_path))
    monkeypatch.delenv("SLATE_TPU_TUNE", raising=False)
    tcache.reset_cache()
    stats.reset()
    yield tmp_path
    tcache.reset_cache()
    stats.reset()


def _mat(n, mb=32, mtype=MatrixType.General, uplo=Uplo.General,
         spd=False, seed=0):
    rng = np.random.default_rng(seed)
    x = rng.standard_normal((n, n)).astype(np.float32)
    if spd:
        x = x @ x.T / n + 4.0 * np.eye(n, dtype=np.float32)
    return TiledMatrix(data=jnp.asarray(x), m=n, n=n, mb=mb, nb=mb,
                       mtype=mtype, uplo=uplo, op=Op.NoTrans,
                       diag=Diag.NonUnit)


# -- cache ---------------------------------------------------------------

def test_cache_roundtrip(tune_env):
    c = tcache.get_cache()
    c.put("getrf", np.float32, 4096, {"nb": 128}, meta={"n": 4096})
    path = c.save()
    assert os.path.dirname(path) == str(tune_env)
    tcache.reset_cache()                       # force re-read from disk
    got = tcache.get_cache().lookup("getrf", np.float32, 4096)
    assert got["nb"] == 128
    assert got["_meta"]["n"] == 4096
    # same bucket, different concrete n: one probe serves the class
    assert tcache.get_cache().get_param(
        "getrf", "nb", np.float32, 3000) == 128
    # different dtype / op / bucket miss
    assert tcache.get_cache().lookup("getrf", np.float64, 4096) is None
    assert tcache.get_cache().lookup("potrf", np.float32, 4096) is None
    assert tcache.get_cache().lookup("getrf", np.float32, 256) is None


def test_cache_version_mismatch_discarded(tune_env):
    p = tcache.cache_path()
    os.makedirs(os.path.dirname(p), exist_ok=True)
    with open(p, "w") as f:
        json.dump({"version": 999, "entries": {
            tcache.make_key("getrf", np.float32, 4096): {"nb": 7}}}, f)
    tcache.reset_cache()
    assert tcache.get_cache().lookup("getrf", np.float32, 4096) is None


def test_cache_corrupt_file_recovery(tune_env):
    p = tcache.cache_path()
    os.makedirs(os.path.dirname(p), exist_ok=True)
    with open(p, "w") as f:
        f.write("{not json at all]]")
    tcache.reset_cache()
    # corrupt file reads as empty, never raises
    assert tcache.get_cache().lookup("getrf", np.float32, 512) is None
    # and the next save overwrites it with a valid versioned file
    tcache.get_cache().put("getrf", np.float32, 512, {"nb": 64})
    tcache.get_cache().save()
    with open(p) as f:
        raw = json.load(f)
    assert raw["version"] == tcache.SCHEMA_VERSION
    tcache.reset_cache()
    assert tcache.get_cache().get_param(
        "getrf", "nb", np.float32, 512) == 64


def test_size_bucket():
    assert tcache.size_bucket(None) == 0
    assert tcache.size_bucket(1) == 256
    assert tcache.size_bucket(256) == 256
    assert tcache.size_bucket(257) == 512
    assert tcache.size_bucket(4096) == 4096
    assert tcache.size_bucket(5000) == 8192


# -- selection precedence ------------------------------------------------

def test_precedence_explicit_over_cached(tune_env):
    c = tcache.get_cache()
    c.put("getrf", np.float32, 1024, {"nb": 128})
    v = select.tuned_int("getrf", "nb", 512,
                         opts={Option.BlockSize: 96},
                         option=Option.BlockSize,
                         n=1024, dtype=np.float32)
    assert v == 96
    # string alias counts as explicit too
    v = select.tuned_int("getrf", "nb", 512, opts={"nb": 80},
                         option=Option.BlockSize,
                         n=1024, dtype=np.float32)
    assert v == 80


def test_precedence_cached_over_frozen(tune_env):
    tcache.get_cache().put("getrf", np.float32, 1024, {"nb": 128})
    v = select.tuned_int("getrf", "nb", 512, n=1024, dtype=np.float32)
    assert v == 128
    snap = stats.snapshot()
    assert snap["decisions"]["getrf.nb[cached]"] == 1


def test_precedence_frozen_when_empty(tune_env):
    v = select.tuned_int("getrf", "nb", 512, n=1024, dtype=np.float32)
    assert v == 512
    assert stats.snapshot()["decisions"]["getrf.nb[frozen]"] == 1


def test_disabled_by_env(tune_env, monkeypatch):
    tcache.get_cache().put("getrf", np.float32, 1024, {"nb": 128})
    monkeypatch.setenv("SLATE_TPU_TUNE", "0")
    v = select.tuned_int("getrf", "nb", 512, n=1024, dtype=np.float32)
    assert v == 512                      # cached entry bypassed


def test_disabled_by_option(tune_env):
    tcache.get_cache().put("getrf", np.float32, 1024, {"nb": 128})
    v = select.tuned_int("getrf", "nb", 512,
                         opts={Option.Tune: False},
                         n=1024, dtype=np.float32)
    assert v == 512


def test_disabled_context(tune_env):
    tcache.get_cache().put("getrf", np.float32, 1024, {"nb": 128})
    with select.disabled():
        assert select.tuned_int("getrf", "nb", 512, n=1024,
                                dtype=np.float32) == 512
    assert select.tuned_int("getrf", "nb", 512, n=1024,
                            dtype=np.float32) == 128


def test_get_option_tuned_plumbs_explicit(tune_env):
    assert get_option_tuned({"ib": 32}, Option.InnerBlocking,
                            "geqrf", n=512) == 32
    assert get_option_tuned(None, Option.InnerBlocking,
                            "geqrf", n=512) == 128   # registry default


# -- frozen table / bit-identical cold start -----------------------------

def test_frozen_table_matches_module_constants(tune_env):
    from slate_tpu.core.options import _DEFAULTS
    from slate_tpu.linalg.eig import SPECTRAL_DC_MIN_N
    from slate_tpu.linalg.spectral_dc import LEAF
    assert tcache.FROZEN[("*", "nb")] == _DEFAULTS[Option.BlockSize]
    assert tcache.FROZEN[("*", "ib")] \
        == _DEFAULTS[Option.InnerBlocking]
    assert tcache.FROZEN[("*", "lookahead")] \
        == _DEFAULTS[Option.Lookahead]
    assert tcache.FROZEN[("heev", "spectral_dc_min_n")] \
        == SPECTRAL_DC_MIN_N
    assert tcache.FROZEN[("heev", "dc_leaf")] == LEAF
    # load-bearing rows (the drivers resolve these with NO literal
    # fallback — the table IS the shipped value)
    assert tcache.FROZEN[("geqrf", "fused_max_n")] == 4096
    assert tcache.FROZEN[("ooc", "panel_cols")] == 8192
    # no-fallback resolution serves the frozen table directly
    assert select.resolve("heev", "spectral_dc_min_n") \
        == SPECTRAL_DC_MIN_N
    assert select.resolve("ooc", "panel_cols") == 8192
    assert select.resolve("geqrf", "fused_max_n") == 4096


def test_kernel_caps_ride_tune_arbitration(tune_env):
    """ISSUE 13 fix pin: the kernel-cap FROZEN rows (('lu_panel',
    'max_w'), ('qr_panel', 'max_w'), ('chol_panel', 'fused_max'),
    ('trtri', 'fused_max')) were ORPHANS — rows nothing read, the
    caps hard-coded at the shape gates (caught by slate_lint SL202).
    The gates now consult the arbitration: a cold cache keeps exactly
    the historical constants, and a measured entry actually moves the
    cap. Size-independent keys (n=None, dtype=None -> bucket 0): one
    row governs the cap."""
    from slate_tpu.ops import pallas_kernels as pk
    # cold cache == the historical constants, both sides of each cap
    assert pk._lu_max_w() == pk.LU_PANEL_MAX_W
    assert pk._qr_shape_ok(4096, pk.QR_PANEL_MAX_W)
    assert not pk._qr_shape_ok(4096, pk.QR_PANEL_MAX_W * 2)
    assert pk._chol_shape_ok(pk.CHOL_FUSED_MAX)
    assert not pk._chol_shape_ok(pk.CHOL_FUSED_MAX * 2)
    assert pk._trtri_shape_ok(pk.TRTRI_FUSED_MAX)
    assert not pk._trtri_shape_ok(pk.TRTRI_FUSED_MAX * 2)
    # a measured entry (a wider-VMEM part's probe) moves each cap
    c = tcache.get_cache()
    c.put("lu_panel", None, None, {"max_w": 64})
    c.put("qr_panel", None, None, {"max_w": pk.QR_PANEL_MAX_W * 2})
    c.put("chol_panel", None, None,
          {"fused_max": pk.CHOL_FUSED_MAX * 2})
    c.put("trtri", None, None, {"fused_max": pk.TRTRI_FUSED_MAX * 2})
    assert pk._lu_max_w() == 64
    assert pk._qr_shape_ok(4096, pk.QR_PANEL_MAX_W * 2)
    assert pk._chol_shape_ok(pk.CHOL_FUSED_MAX * 2)
    assert pk._trtri_shape_ok(pk.TRTRI_FUSED_MAX * 2)


def test_empty_cache_selects_todays_defaults(tune_env, monkeypatch):
    """Acceptance: probing disabled + empty cache => every wired knob
    resolves to the pre-tune value, and the drivers' outputs are
    bit-identical to a run with tuning hard-disabled."""
    from slate_tpu.linalg.lu import _lu_nb
    # the knob-level frozen values
    assert _lu_nb(None, 512, (4096, 4096), None) == 512
    assert _lu_nb(None, 512, (16384, 16384), None) == 1024
    assert select.tuned_int("heev", "spectral_dc_min_n", 2048,
                            n=4096, dtype=np.float32) == 2048
    from slate_tpu.linalg.ooc import _panel_cols
    assert _panel_cols(None, 65536, np.float32) == 8192
    assert _panel_cols(128, 65536, np.float32) == 128  # explicit wins

    # driver-level bit-identical routing: tuning enabled w/ empty
    # cache vs tuning disabled must produce byte-equal factors
    outs = {}
    for mode in ("enabled", "disabled"):
        if mode == "disabled":
            monkeypatch.setenv("SLATE_TPU_TUNE", "0")
        else:
            monkeypatch.delenv("SLATE_TPU_TUNE", raising=False)
        H = _mat(64, spd=True, mtype=MatrixType.Hermitian,
                 uplo=Uplo.Lower)
        G = _mat(64)
        outs[mode] = (
            np.asarray(st.potrf(H).data),
            np.asarray(st.getrf(G).LU.data),
            np.asarray(st.geqrf(G).QR.data),
            np.asarray(st.heev(H).values),
        )
    for a, b in zip(outs["enabled"], outs["disabled"]):
        assert np.array_equal(a, b)


# -- cached method routing ----------------------------------------------

def test_cached_method_eig_routes_auto(tune_env):
    n = 32
    tcache.get_cache().put("heev", np.float32, n,
                           {"method_eig": "qr_iteration"})
    H = _mat(n, spd=True, mtype=MatrixType.Hermitian, uplo=Uplo.Lower)
    r = st.heev(H)                        # Auto -> cached QRIteration
    assert stats.snapshot()["decisions"].get(
        "heev.method_eig[cached]", 0) >= 1
    wref = np.linalg.eigvalsh(np.asarray(H.to_dense(), np.float64))
    assert np.allclose(np.asarray(r.values), wref, atol=1e-3)
    # explicit method still wins over the cache (no cached decision)
    stats.reset()
    from slate_tpu.core.methods import MethodEig
    st.heev(H, {Option.MethodEig: MethodEig.Auto})
    # explicit Auto short-circuits tuned_method entirely
    assert "heev.method_eig[cached]" not in \
        stats.snapshot()["decisions"]


def test_cached_unknown_method_ignored(tune_env):
    tcache.get_cache().put("heev", np.float32, 32,
                           {"method_eig": "not_a_method"})
    H = _mat(32, spd=True, mtype=MatrixType.Hermitian, uplo=Uplo.Lower)
    r = st.heev(H)                         # falls through to Auto
    wref = np.linalg.eigvalsh(np.asarray(H.to_dense(), np.float64))
    assert np.allclose(np.asarray(r.values), wref, atol=1e-3)


def test_cached_blocksize_drives_getrf(tune_env):
    """A cached nb both changes the selected value and keeps the
    factorization correct."""
    n = 96
    tcache.get_cache().put("getrf", np.float32, n, {"nb": 32})
    G = _mat(n)
    F = st.getrf(G)
    assert stats.snapshot()["decisions"]["getrf.nb[cached]"] >= 1
    lu = np.asarray(F.LU.data)
    l = np.tril(lu, -1) + np.eye(n)
    u = np.triu(lu)
    perm = np.arange(n)
    for j, t in enumerate(np.asarray(F.pivots)):
        perm[j], perm[t] = perm[t], perm[j]
    a = np.asarray(G.data)
    assert np.allclose(l @ u, a[perm], atol=1e-4)


def test_getrf_blocksize_zero_means_default(tune_env):
    """Historical contract: an explicit Option.BlockSize of 0 means
    'use the default', it must not become a zero panel width."""
    n = 64
    G = _mat(n)
    F = st.getrf(G, {Option.BlockSize: 0})
    lu = np.asarray(F.LU.data)
    assert np.isfinite(lu).all()
    F2 = st.getrf(G)
    assert np.array_equal(lu, np.asarray(F2.LU.data))


# -- probe smoke (CPU backend) -------------------------------------------

def test_probe_smoke_cpu(tune_env):
    from slate_tpu.tune import probe
    report = probe.autotune(ops=("potrf",), n=64,
                            nb_candidates=(32, 64), reps=1,
                            write=True)
    results = report["potrf"]["results"]
    # driver-default baseline (nb=None) + the two candidates
    assert len(results) == 3
    assert any(r["nb"] is None for r in results)
    assert all(r["seconds"] > 0 for r in results)
    assert os.path.exists(report["_cache_path"])
    snap = stats.snapshot()
    assert snap["probe_seconds"] > 0
    tcache.reset_cache()
    chosen = report["potrf"]["chosen"]
    if chosen:
        # a winner beat the default: persisted and served
        assert chosen["nb"] in (32, 64)
        assert select.tuned_int("potrf", "nb", 256, n=64,
                                dtype=np.float32) == chosen["nb"]
    else:
        # the default won: nothing cached (never-regress), frozen
        # fallback served
        assert select.tuned_int("potrf", "nb", 256, n=64,
                                dtype=np.float32) == 256


def test_cached_geqrf_routes_tiled_and_nb(tune_env):
    """A geqrf probe winner is cached as {nb, fused_max_n: 0}; the
    driver must then route Auto past the Fused crossover and consult
    the cached nb (both decisions visible in the counters)."""
    n = 96
    tcache.get_cache().put("geqrf", np.float32, n,
                           {"nb": 32, "fused_max_n": 0})
    G = _mat(n)
    F = st.geqrf(G)
    d = stats.snapshot()["decisions"]
    assert d.get("geqrf.fused_max_n[cached]", 0) >= 1
    assert d.get("geqrf.nb[cached]", 0) >= 1
    # and the factorization stays correct (R diag magnitudes)
    r_ = np.triu(np.asarray(F.QR.data))[:n]
    rref = np.linalg.qr(np.asarray(G.data), mode="r")
    assert np.allclose(np.abs(np.diag(r_)), np.abs(np.diag(rref)),
                       rtol=1e-3, atol=1e-4)


def test_measure_separates_warmup():
    from slate_tpu.tune.probe import measure
    calls = []

    def fn():
        calls.append(1)
        return jnp.zeros(())

    t = measure(fn, warmup=2, reps=2, min_time=0.0)
    assert t >= 0
    assert len(calls) >= 5            # 2 warmup + sizing + 2 reps


# -- polar.py invariant regressions (ADVICE r5) --------------------------

def test_polar_dip_region_sigma():
    """A singular value at the capped-weight dip (~0.12 in f32) used
    to make the lifted l exceed the true sigma_min (broken lower-bound
    invariant); the interval-minimum lift must keep the iteration
    convergent and the sign exact."""
    from slate_tpu.linalg.polar import polar_unitary
    n = 48
    d = np.linspace(0.5, 1.0, n).astype(np.float32)
    d[0], d[1] = 0.12, -0.12
    u, k, conv = polar_unitary(jnp.asarray(np.diag(d)))
    u = np.asarray(u)
    assert bool(conv)
    assert np.abs(u @ u.T - np.eye(n)).max() < 5e-5
    assert np.abs(u - np.diag(np.sign(d))).max() < 5e-5


def test_polar_clustered_small_sigmas():
    """Clustered tiny singular values leave the 4-step power iteration
    short of lambda_max; the convergence-gated `reliable` flag must
    prevent an overshot lift from stalling the schedule."""
    from slate_tpu.linalg.polar import polar_unitary
    n = 48
    d = np.full(n, 1e-4, np.float32)
    d[n // 2:] = 1.0
    d[::2] *= -1.0
    u, k, conv = polar_unitary(jnp.asarray(np.diag(d)))
    u = np.asarray(u)
    assert bool(conv)
    assert int(k) <= 14
    assert np.abs(u - np.diag(np.sign(d))).max() < 5e-5


def test_polar_lift_is_interval_minimum():
    """Direct pin of the fixed invariant: the schedule lift
    _lift_estimate(sg, a, b, c) must lower-bound f over ALL of
    [sg, 1], not just at sg (f is non-monotone under capped
    weights)."""
    from slate_tpu.linalg.polar import (C_MAX_F32, _capped_params,
                                        _lift_estimate)
    for l in (1e-8, 1e-6, 1e-4, 1e-2, 0.1):
        a, b, c, _ = _capped_params(jnp.float32(l), C_MAX_F32)
        for sg in (1e-5, 1e-3, 0.05, 0.11, 0.3, 0.8):
            lest = float(_lift_estimate(jnp.float32(sg), a, b, c))
            xs = np.linspace(sg, 1.0, 20001)
            f = xs * (float(a) + float(b) * xs ** 2) \
                / (1 + float(c) * xs ** 2)
            assert lest <= f.min() + 1e-7, (l, sg, lest, f.min())


def test_polar_estimator_key_varies_with_iteration():
    """The estimator start block folds the iteration counter into its
    PRNG key (no fixed-PRNGKey(7) retry loop)."""
    from slate_tpu.linalg.polar import _chol_halley_step
    n = 32
    rng = np.random.default_rng(3)
    x = rng.standard_normal((n, n)).astype(np.float32)
    u = jnp.asarray(x / np.linalg.norm(x, 2))
    a = jnp.float32(3.0)
    b = jnp.float32(1.0)
    c = jnp.float32(3.0)
    _, sig0, _ = _chol_halley_step(u, a, b, c, want_sigma_est=True,
                                   it=0)
    _, sig1, _ = _chol_halley_step(u, a, b, c, want_sigma_est=True,
                                   it=1)
    # different fold leads to a (generically) different estimate;
    # both remain finite and nonnegative
    assert np.isfinite(float(sig0)) and np.isfinite(float(sig1))
    assert float(sig0) >= 0 and float(sig1) >= 0


def test_eigh_dc_propagates_polar_convergence():
    """eigh_dc surfaces the AND of every split's polar converged flag
    (previously discarded at spectral_dc.py:128)."""
    from slate_tpu.linalg.spectral_dc import eigh_dc
    rng = np.random.default_rng(1)
    x = rng.standard_normal((256, 256)).astype(np.float32)
    h = (x + x.T) / 2
    w, v, ok = eigh_dc(jnp.asarray(h), leaf=128)
    assert bool(ok)
    wref = np.linalg.eigvalsh(h.astype(np.float64))
    assert np.abs(np.asarray(w) - wref).max() < 1e-3
    v = np.asarray(v)
    assert np.abs(v.T @ v - np.eye(256)).max() < 1e-4
    # leaf-only path returns the flag too (trivially True)
    w2, v2, ok2 = eigh_dc(jnp.asarray(h), leaf=256)
    assert bool(ok2)
