"""OOC streaming engine v2 (linalg/stream.py): the panel-residency
cache + async pipeline must be INVISIBLE numerically — cache-on
results bit-identical to cache-off for every OOC driver, including
under forced eviction and under getrf's row-swap invalidation — while
measurably cutting the left-looking H2D revisit volume (the ISSUE 4
acceptance: >= 40% reduction at nt >= 8 with a budget holding >= nt/2
panels, read from the obs metrics snapshot)."""

import numpy as np
import pytest

from slate_tpu.linalg import ooc, stream
from slate_tpu.linalg.stream import PanelCache, StreamEngine


@pytest.fixture
def rng():
    return np.random.default_rng(77)


@pytest.fixture
def obs_on():
    """Event bus + metrics on, reset around the test."""
    from slate_tpu import obs
    from slate_tpu.obs import metrics
    obs.enable()
    obs.clear()
    metrics.reset()
    yield obs
    obs.disable()
    obs.clear()
    metrics.reset()


def _spd(rng, n, dtype=np.float64):
    x = rng.standard_normal((n, n)).astype(dtype)
    return x @ x.T / n + 4.0 * np.eye(n, dtype=dtype)


# -- PanelCache unit behavior ---------------------------------------------

def _arr(nbytes):
    return np.zeros(nbytes // 8, np.float64)


def test_panel_cache_lru_vs_mru_eviction():
    """lru evicts the least recently served unpinned entry; mru the
    most recent one (the cyclic-scan policy the frozen default ships
    — LRU degenerates to zero hits on a left-looking revisit once
    the factor outgrows the budget)."""
    for policy, evicted in (("lru", 2), ("mru", 3)):
        c = PanelCache(budget_bytes=4 * 800, policy=policy)
        for i in range(4):
            assert c.put(("L", 0, i), _arr(800))
        # bump recency AND pin {0, 1} (get pins; deque maxlen=2):
        # recency order is now 2 < 3 < 0 < 1
        assert c.get(("L", 0, 0)) is not None
        assert c.get(("L", 0, 1)) is not None
        assert c.put(("L", 0, 4), _arr(800))
        held = {k[2] for k in c._entries}
        assert evicted not in held, (policy, held)
        assert held == {0, 1, 2, 3, 4} - {evicted}
        assert c.evictions == 1


def test_panel_cache_pinning_and_overbudget():
    c = PanelCache(budget_bytes=1000, policy="mru")
    assert not c.put(("L", 0, 0), _arr(1600))   # alone over budget
    assert c.put(("L", 0, 1), _arr(800))
    # pins hold the only entry: a second insert finds no victim
    assert not c.put(("L", 0, 2), _arr(800))
    assert c.get(("L", 0, 1)) is not None
    assert c.hits == 1 and c.misses == 0


def test_panel_cache_epoch_invalidation():
    """invalidate() bumps the buffer epoch: old entries are dropped
    and the NEW key no longer matches them — the getrf row-swap
    wrong-answer guard at the cache layer."""
    c = PanelCache(budget_bytes=10_000, policy="mru")
    k0 = c.key("LU", 0)
    c.put(k0, _arr(800))
    assert c.get(k0) is not None
    dropped = c.invalidate("LU")
    assert dropped == 1 and c.invalidations == 1
    k1 = c.key("LU", 0)
    assert k1 != k0
    assert c.get(k1) is None            # stale entry not served
    assert c.resident_bytes == 0


def test_engine_budget_zero_is_uncached():
    """The frozen-default budget (0) disables the cache entirely —
    the budget contract every driver's cold start rides on."""
    eng = stream.engine_for(256, 32, np.float64)
    try:
        assert not eng.caching
        assert eng.cache.budget == 0
    finally:
        eng.finish()


def test_engine_auto_budget_never_invents_memory(monkeypatch):
    """"auto" derives from the device's reported bytes_limit minus
    the working-set reserve; an unreporting backend yields 0 (cache
    off), never a made-up budget."""
    import jax

    class _Dev:
        def __init__(self, stats):
            self._stats = stats

        def memory_stats(self):
            return self._stats

    # backend reports no limit (CPU-style): auto MUST resolve to 0
    monkeypatch.setattr(jax, "local_devices", lambda: [_Dev({})])
    assert stream.auto_budget_bytes(1 << 20, 8192, 4) == 0
    eng = stream.engine_for(64, 16, np.float64, budget_bytes="auto")
    try:
        assert eng.cache.budget == 0 and not eng.caching
    finally:
        eng.finish()
    # HBM-style limit: 90% headroom minus the 4-panel reserve
    limit = 16 << 30
    monkeypatch.setattr(jax, "local_devices",
                        lambda: [_Dev({"bytes_limit": limit})])
    n, w, item = 1 << 16, 8192, 4
    expect = int(limit * stream.AUTO_BUDGET_FRACTION) \
        - stream.RESERVE_PANELS * n * w * item
    assert stream.auto_budget_bytes(n, w, item) == expect
    # a reserve larger than the device clamps to 0, never negative
    assert stream.auto_budget_bytes(1 << 22, 1 << 20, 8) == 0
    with pytest.raises(ValueError, match="auto"):
        stream.engine_for(64, 16, np.float64, budget_bytes="never")


def test_d2h_writes_into_preallocated_slice(rng):
    """_d2h(out=...) fills the caller's slice chunk-by-chunk (no
    concatenate copy), including non-contiguous column views and the
    chunked >=2048-row path."""
    import jax.numpy as jnp
    x = rng.standard_normal((2304, 6))
    d = jnp.asarray(x)
    host = np.zeros((2304, 10))
    got = ooc._d2h(d, out=host[:, 2:8])
    np.testing.assert_array_equal(host[:, 2:8], np.asarray(d))
    assert got.base is host or got.shape == (2304, 6)
    # small path too
    h2 = np.zeros((64, 6))
    ooc._d2h(d[:64], out=h2)
    np.testing.assert_array_equal(h2, np.asarray(d)[:64])


# -- cache-on == cache-off, driver by driver ------------------------------

def test_ooc_drivers_cache_bit_identical_under_eviction(rng):
    """Every OOC driver: a budget too small for the factor (evictions
    forced) and a comfortable budget both reproduce the budget-0
    result EXACTLY. tiny n, panels much smaller than the matrix."""
    n, w = 160, 32
    tiny = int(1.5 * n * w * 8)          # ~1.5 panels -> evictions
    big = 64 * n * w * 8
    a = _spd(rng, n)
    g = rng.standard_normal((n, n))
    b = rng.standard_normal((n, 3))

    L0 = ooc.potrf_ooc(a, panel_cols=w, cache_budget_bytes=0)
    for budget in (tiny, big):
        Lc = ooc.potrf_ooc(a, panel_cols=w, cache_budget_bytes=budget)
        np.testing.assert_array_equal(L0, Lc)
        xc = ooc.potrs_ooc(L0, b, panel_cols=w,
                           cache_budget_bytes=budget)
        np.testing.assert_array_equal(
            ooc.potrs_ooc(L0, b, panel_cols=w, cache_budget_bytes=0),
            xc)

    lu0, piv0 = ooc.getrf_ooc(g, panel_cols=w, cache_budget_bytes=0)
    x0 = ooc.getrs_ooc(lu0, piv0, b, panel_cols=w,
                       cache_budget_bytes=0)
    qr0, tau0 = ooc.geqrf_ooc(g, panel_cols=w, cache_budget_bytes=0)
    y0 = ooc.unmqr_ooc(qr0, tau0, b, trans=True, panel_cols=w,
                       cache_budget_bytes=0)
    for budget in (tiny, big):
        lu1, piv1 = ooc.getrf_ooc(g, panel_cols=w,
                                  cache_budget_bytes=budget)
        np.testing.assert_array_equal(lu0, lu1)
        np.testing.assert_array_equal(piv0, piv1)
        np.testing.assert_array_equal(
            x0, ooc.getrs_ooc(lu0, piv0, b, panel_cols=w,
                              cache_budget_bytes=budget))
        qr1, tau1 = ooc.geqrf_ooc(g, panel_cols=w,
                                  cache_budget_bytes=budget)
        np.testing.assert_array_equal(qr0, qr1)
        np.testing.assert_array_equal(tau0, tau1)
        np.testing.assert_array_equal(
            y0, ooc.unmqr_ooc(qr0, tau0, b, trans=True, panel_cols=w,
                              cache_budget_bytes=budget))


def test_ooc_composite_drivers_cache_bit_identical(rng):
    """posv/gesv/gels/gemm through the engine: budgeted == budget-0,
    bit for bit (gels exercises the shared factor->apply->R-sweep
    engine; gemm the pipeline-only path)."""
    n, w = 128, 32
    budget = 3 * n * w * 8
    a = _spd(rng, n)
    g = rng.standard_normal((n, n)) + 0.2 * n * np.eye(n)
    b = rng.standard_normal((n, 2))
    L0, x0 = ooc.posv_ooc(a, b, panel_cols=w, cache_budget_bytes=0)
    L1, x1 = ooc.posv_ooc(a, b, panel_cols=w,
                          cache_budget_bytes=budget)
    np.testing.assert_array_equal(L0, L1)
    np.testing.assert_array_equal(x0, x1)
    (lu0, p0), y0 = ooc.gesv_ooc(g, b, panel_cols=w,
                                 cache_budget_bytes=0)
    (lu1, p1), y1 = ooc.gesv_ooc(g, b, panel_cols=w,
                                 cache_budget_bytes=budget)
    np.testing.assert_array_equal(lu0, lu1)
    np.testing.assert_array_equal(p0, p1)
    np.testing.assert_array_equal(y0, y1)
    m, k = 200, 64
    ta = rng.standard_normal((m, k))
    tb = rng.standard_normal((m, 2))
    (_, _), z0 = ooc.gels_ooc(ta, tb, panel_cols=32,
                              cache_budget_bytes=0)
    (_, _), z1 = ooc.gels_ooc(ta, tb, panel_cols=32,
                              cache_budget_bytes=budget)
    np.testing.assert_array_equal(z0, z1)
    c = rng.standard_normal((m, 5))
    bb = rng.standard_normal((k, 5))
    g0 = ooc.gemm_ooc(1.5, ta, bb, -0.5, c, row_panel=64,
                      cache_budget_bytes=0)
    g1 = ooc.gemm_ooc(1.5, ta, bb, -0.5, c, row_panel=64,
                      cache_budget_bytes=budget)
    np.testing.assert_array_equal(g0, g1)


def test_getrf_ooc_rowswap_invalidates_stale_panels(rng):
    """The wrong-answer guard (ISSUE 4): getrf's host-side row-swap
    fixup rewrites rows of already-written L panels — the epoch bump
    must retire their cached device copies, or later visits would be
    served pre-swap rows. The input is built to pivot ACROSS panel
    boundaries at every step (later rows strictly dominate), so a
    stale-cache bug cannot hide; with the guard, cached == uncached
    == in-core, bit for bit on the pivot sequence."""
    import slate_tpu as st
    n, w = 128, 32
    a = rng.standard_normal((n, n))
    # growing magnitudes toward the bottom: every panel's pivot
    # search selects rows from LATER panels -> cross-panel swaps
    a *= (1.0 + np.arange(n))[:, None]
    lu0, piv0 = ooc.getrf_ooc(a, panel_cols=w, cache_budget_bytes=0)
    lu1, piv1 = ooc.getrf_ooc(a, panel_cols=w,
                              cache_budget_bytes=64 * n * w * 8)
    s = stream.last_stats()
    assert s["invalidations"] > 0, \
        "input did not exercise the row-swap fixup"
    np.testing.assert_array_equal(piv0, piv1)
    np.testing.assert_array_equal(lu0, lu1)
    F = st.getrf(st.Matrix(a, mb=w))
    np.testing.assert_array_equal(piv1, np.asarray(F.pivots)[:n])


def test_prefetch_depth_and_policy_knobs_bit_identical(rng,
                                                       monkeypatch):
    """Turning the async H2D prefetch off (depth 0) and switching the
    eviction policy must not change a single bit — the pipeline is a
    scheduling change only. Knobs flow through tune/select's FROZEN
    table (the registration path)."""
    from slate_tpu.tune import cache as tcache
    n, w = 160, 32
    a = _spd(rng, n)
    budget = 3 * n * w * 8
    ref = ooc.potrf_ooc(a, panel_cols=w, cache_budget_bytes=budget)
    monkeypatch.setitem(tcache.FROZEN, ("ooc", "prefetch_depth"), 0)
    monkeypatch.setitem(tcache.FROZEN, ("ooc", "cache_policy"), "lru")
    got = ooc.potrf_ooc(a, panel_cols=w, cache_budget_bytes=budget)
    np.testing.assert_array_equal(ref, got)
    monkeypatch.setitem(tcache.FROZEN, ("ooc", "cache_policy"),
                        "fifo")
    got = ooc.potrf_ooc(a, panel_cols=w, cache_budget_bytes=budget)
    np.testing.assert_array_equal(ref, got)


# -- transfer-volume acceptance (obs snapshot) ----------------------------

def test_potrf_cache_cuts_h2d_volume(rng, obs_on):
    """ISSUE 4 acceptance: at nt=8 panels with a budget holding >=
    nt/2 panels, the residency cache cuts ooc.h2d_bytes by >= 40%
    for a left-looking factorization, with hit/miss/eviction
    counters present in the obs snapshot."""
    from slate_tpu.obs import metrics
    n, w = 256, 32          # nt = 8
    a = _spd(rng, n)
    L0 = ooc.potrf_ooc(a, panel_cols=w, cache_budget_bytes=0)
    base = metrics.snapshot()["counters"]["ooc.h2d_bytes"]
    assert base > 0
    metrics.reset()
    budget = 6 * n * w * 8          # 6 full panels (>= nt/2 = 4)
    L1 = ooc.potrf_ooc(a, panel_cols=w, cache_budget_bytes=budget)
    c = metrics.snapshot()["counters"]
    np.testing.assert_array_equal(L0, L1)
    cached = c["ooc.h2d_bytes"]
    assert cached <= 0.6 * base, \
        "h2d reduction %.1f%% < 40%% (base %d, cached %d)" \
        % (100 * (1 - cached / base), base, cached)
    # counters the bench extras / report surface
    assert c["ooc.cache.hits"] > 0
    assert "ooc.cache.misses" in c
    assert "ooc.cache.evictions" in c
    assert c["ooc.cache.served_bytes"] > 0
    assert c["ooc.prefetch.issued"] > 0


def test_geqrf_cache_cuts_h2d_volume(rng, obs_on):
    """Same acceptance shape for the reflector-panel stream (no
    invalidation path): first visit uploads, later visits hit."""
    from slate_tpu.obs import metrics
    n, w = 256, 32
    g = rng.standard_normal((n, n))
    qr0, _ = ooc.geqrf_ooc(g, panel_cols=w, cache_budget_bytes=0)
    base = metrics.snapshot()["counters"]["ooc.h2d_bytes"]
    metrics.reset()
    qr1, _ = ooc.geqrf_ooc(g, panel_cols=w,
                           cache_budget_bytes=8 * n * w * 8)
    c = metrics.snapshot()["counters"]
    np.testing.assert_array_equal(qr0, qr1)
    assert c["ooc.h2d_bytes"] <= 0.6 * base
    assert c["ooc.cache.hits"] > 0


def test_solve_drivers_instrumented(rng, obs_on):
    """Satellite: potrs/getrs/posv/unmqr_ooc now carry
    @instrument_driver — their spans and call counters land in the
    obs snapshot like the factor drivers'."""
    from slate_tpu import obs
    n, w = 96, 32
    a = _spd(rng, n)
    b = rng.standard_normal((n, 2))
    L, _ = ooc.posv_ooc(a, b, panel_cols=w)
    ooc.potrs_ooc(L, b, panel_cols=w)
    g = rng.standard_normal((n, n)) + 0.2 * n * np.eye(n)
    lu, piv = ooc.getrf_ooc(g, panel_cols=w)
    ooc.getrs_ooc(lu, piv, b, panel_cols=w)
    qr, tau = ooc.geqrf_ooc(g, panel_cols=w)
    ooc.unmqr_ooc(qr, tau, b, panel_cols=w)
    drv = obs.snapshot()["drivers"]
    for op in ("posv_ooc", "potrs_ooc", "getrs_ooc", "unmqr_ooc"):
        assert drv[op]["calls"] >= 1, op


def test_gemm_and_getrf_uploads_counted(rng, obs_on):
    """Satellite: gemm_ooc's B/A/C uploads and getrf_ooc's permuted
    panel read are routed through _h2d, so ooc.h2d_bytes covers the
    FULL transfer volume (it used to undercount the jnp.asarray
    paths)."""
    from slate_tpu.obs import metrics
    m, k = 128, 48
    a = rng.standard_normal((m, k))
    bb = rng.standard_normal((k, 4))
    c = rng.standard_normal((m, 4))
    ooc.gemm_ooc(1.0, a, bb, 1.0, c, row_panel=64)
    got = metrics.snapshot()["counters"]["ooc.h2d_bytes"]
    expect = a.nbytes + bb.nbytes + c.nbytes
    assert got >= expect, (got, expect)
    metrics.reset()
    g = rng.standard_normal((96, 96))
    ooc.getrf_ooc(g, panel_cols=32)
    got = metrics.snapshot()["counters"]["ooc.h2d_bytes"]
    assert got >= g.nbytes          # every panel read counted once


def test_engine_stats_surface():
    """stream.last_stats() carries the fields bench --ooc ships."""
    rng = np.random.default_rng(3)
    a = _spd(rng, 96)
    ooc.potrf_ooc(a, panel_cols=32, cache_budget_bytes=6 * 96 * 32 * 8)
    s = stream.last_stats()
    for key in ("hits", "misses", "evictions", "invalidations",
                "hit_rate", "served_bytes", "prefetch_issued",
                "prefetch_overlap_fraction", "d2h_overlap_fraction",
                "budget_bytes", "policy"):
        assert key in s, key
    assert s["hits"] > 0
