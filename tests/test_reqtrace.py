"""Request tracing + SLO telemetry coverage (ISSUE 18): the FROZEN
off-state pins (zero spans/series, no RPC header growth, bitwise
results vs the untraced path), end-to-end trace continuity across
client/server/admission/flush/response, the quantile-sketch accuracy
contract vs np.percentile, SLO burn feeding the admission ladder with
the violated objective in the escalation payload, the metrics RPC
round-trip, and the Perfetto flow-event export pin."""

import collections
import threading

import numpy as np
import pytest

from slate_tpu import obs
from slate_tpu.batch import queue as bq
from slate_tpu.obs import events as oe
from slate_tpu.obs import ledger as oledger
from slate_tpu.obs import metrics as om
from slate_tpu.obs import reqtrace, series
from slate_tpu.resil import faults, guard
from slate_tpu.serve import rpc as srpc
from slate_tpu.serve.admission import (AdmissionController, DEGRADE,
                                       SHED, TenantConfig)
from slate_tpu.serve.server import Server


@pytest.fixture(autouse=True)
def _clean_state():
    """Tracing tests leave no process-wide obs/serve state behind."""
    yield
    reqtrace.reset()
    series.reset()
    oledger.reset()
    obs.disable()
    oe.clear()
    om.reset()
    guard.reset_counts()
    faults.clear()


def _spd(n, dtype=np.float32, seed=0):
    rng = np.random.default_rng(seed)
    x = rng.standard_normal((n, n)).astype(dtype)
    return x @ x.T + 2.0 * n * np.eye(n, dtype=dtype)


def _rhs(n, k=2, dtype=np.float32, seed=1):
    return np.random.default_rng(seed).standard_normal(
        (n, k)).astype(dtype)


def _server(**kw):
    return Server(queue=bq.CoalescingQueue(background=False), **kw)


# -- the FROZEN off-state -------------------------------------------------

def test_frozen_rows_ship_off():
    from slate_tpu.tune.select import resolve
    assert str(resolve("obs", "reqtrace")) == "off"
    assert str(resolve("serve", "metrics")) == "off"
    assert not reqtrace.enabled()
    assert not series.enabled()
    assert reqtrace.begin(tenant="t", op="potrf") is None


def test_off_state_records_nothing():
    with _server() as srv:
        t = srv.submit("potrf", _spd(16))
        t.result(timeout=60)
        assert t.span is None
    assert reqtrace.count() == 0
    assert series.snapshot() == {"series": {}, "slo": {}}
    assert series.render_prometheus() == ""


def test_off_state_rpc_wire_unchanged(monkeypatch):
    """With tracing off NEITHER side adds a header field: the frames
    on the wire are exactly the PR 17 shape (pinned via a _send_frame
    spy on both client and server)."""
    headers = []
    real = srpc._send_frame

    def spy(sock, header, payloads=()):
        headers.append(dict(header))
        return real(sock, header, payloads)

    monkeypatch.setattr(srpc, "_send_frame", spy)
    with _server() as srv, srpc.RpcServer(srv) as rs, \
            srpc.RpcClient(rs.address) as cl:
        out = cl.submit("potrf", _spd(16))
        assert np.asarray(out).shape == (16, 16)
        assert cl.last_trace is None
    assert headers                       # both directions captured
    for h in headers:
        assert "trace" not in h and "span" not in h


def test_traced_results_bitwise_vs_untraced():
    """Tracing ON never perturbs numerics: direct and RPC results are
    bitwise-identical to the untraced run on the same inputs."""
    a, b = _spd(24, seed=3), _rhs(24, seed=4)
    with _server() as srv:
        ref_f = np.asarray(srv.submit("potrf", a.copy())
                           .result(timeout=60))
        ref_s = np.asarray(srv.submit("posv", a.copy(), b.copy())
                           .result(timeout=60))
    reqtrace.enable()
    series.enable()
    with _server() as srv:
        got_f = np.asarray(srv.submit("potrf", a.copy())
                           .result(timeout=60))
        got_s = np.asarray(srv.submit("posv", a.copy(), b.copy())
                           .result(timeout=60))
    assert np.array_equal(ref_f, got_f)
    assert np.array_equal(ref_s, got_s)
    with _server() as srv, srpc.RpcServer(srv) as rs, \
            srpc.RpcClient(rs.address) as cl:
        got_r = np.asarray(cl.submit("posv", a.copy(), b.copy()))
    assert np.array_equal(ref_s, got_r)


# -- trace continuity -----------------------------------------------------

def test_direct_span_carries_phase_split_and_flush_link():
    reqtrace.enable()
    with _server() as srv:
        t = srv.submit("potrf", _spd(16), tenant="acme")
        t.result(timeout=60)
    sp = t.span
    assert sp is not None and sp.t1 is not None
    assert sp.name == reqtrace.REQUEST_SPAN
    assert sp.tenant == "acme" and sp.op == "potrf"
    for ph in ("admit_s", "queue_wait_s", "dispatch_s", "solve_s"):
        assert sp.phases[ph] >= 0.0
    # wall >= sum of the measured slices (no phase double-counts)
    assert sp.t1 - sp.t0 >= sum(
        sp.phases[p] for p in ("queue_wait_s", "dispatch_s",
                               "solve_s")) - 1e-6
    fid = sp.args["flush_id"]
    flushes = [f for f in reqtrace.spans(reqtrace.FLUSH_SPAN)
               if f.args["flush_id"] == fid]
    assert len(flushes) == 1
    assert sp.trace_id in flushes[0].args["trace_ids"]
    assert flushes[0].args["occupancy"] >= 1


def test_rpc_trace_continuity_one_trace_id():
    """ONE trace_id spans client rpc span, server root, and the flush
    linkage — and the response echoes it back to the client."""
    reqtrace.enable()
    with _server() as srv, srpc.RpcServer(srv) as rs, \
            srpc.RpcClient(rs.address) as cl:
        cl.submit("potrf", _spd(16), tenant="acme")
        tid = cl.last_trace
    assert tid is not None
    tspans = reqtrace.trace(tid)
    by_name = {s.name: s for s in tspans}
    assert set(by_name) >= {reqtrace.CLIENT_SPAN,
                            reqtrace.REQUEST_SPAN}
    client = by_name[reqtrace.CLIENT_SPAN]
    root = by_name[reqtrace.REQUEST_SPAN]
    # the server root is a CHILD of the client span (header "span")
    assert root.parent_id == client.span_id
    assert root.trace_id == client.trace_id == tid
    fid = root.args["flush_id"]
    flushes = [f for f in reqtrace.spans(reqtrace.FLUSH_SPAN)
               if f.args["flush_id"] == fid]
    assert tid in flushes[0].args["trace_ids"]


def test_cobatched_requests_share_one_flush():
    reqtrace.enable()
    with _server() as srv:
        ts = [srv.submit("potrf", _spd(16, seed=s), tenant="t%d" % s)
              for s in range(3)]
        for t in ts:
            t.result(timeout=60)
    fids = {t.span.args["flush_id"] for t in ts}
    assert len(fids) == 1                # one co-batched flush
    (fid,) = fids
    fl = [f for f in reqtrace.spans(reqtrace.FLUSH_SPAN)
          if f.args["flush_id"] == fid][0]
    assert sorted(fl.args["trace_ids"]) \
        == sorted(t.span.trace_id for t in ts)
    assert fl.args["occupancy"] == 3


def test_cache_miss_hit_paths_traced():
    """The factor-cache route keeps the trace: the shared factor
    dispatch is a child span of the first miss, hits stamp the cache
    outcome, and solve requests still close with a flush link."""
    reqtrace.enable()
    oe.enable()
    a, b = _spd(16, seed=5), _rhs(16, seed=6)
    with _server(cache_mb=16) as srv:
        t1 = srv.submit("posv", a, b, tenant="acme")
        t1.result(timeout=60)
        t2 = srv.submit("posv", a, b, tenant="acme")
        t2.result(timeout=60)
    assert t1.span.args["cache"] == "miss"
    assert t2.span.args["cache"] == "hit"
    kids = [s for s in reqtrace.trace(t1.span.trace_id)
            if s.name == "serve::factor"]
    assert len(kids) == 1
    assert kids[0].parent_id == t1.span.span_id
    assert "flush_id" in kids[0].args
    # the cache outcome instants carry the trace ids
    outcomes = {}
    for e in oe.events(cat="serve"):
        if e.name == "serve::cache":
            outcomes[e.args["trace"]] = e.args["outcome"]
    assert outcomes[t1.span.trace_id] == "miss"
    assert outcomes[t2.span.trace_id] == "hit"


def test_span_closure_feeds_series_and_ledger():
    reqtrace.enable()
    series.enable()
    oledger.enable()
    with _server() as srv:
        t = srv.submit("potrf", _spd(16), tenant="acme")
        t.result(timeout=60)
    q = series.quantiles("serve.latency_s", tenant="acme",
                         op="potrf")
    assert q is not None and q["p50"] > 0.0
    assert series.get("serve.queue_wait_s", tenant="acme",
                      op="potrf") is not None
    recs = oledger.records("serve.request")
    assert len(recs) == 1
    assert recs[0].meta["trace"] == t.span.trace_id
    assert recs[0].meta["tenant"] == "acme"
    assert recs[0].phases["other"] > 0.0


def test_error_closes_span():
    reqtrace.enable()
    faults.install(faults.FaultPlan([
        {"site": "serve_admit", "times": 1}]))
    with _server() as srv:
        with pytest.raises(Exception):
            srv.submit("potrf", _spd(16))
    faults.clear()
    # the root never opened (fault fired before begin) or closed with
    # an error — either way nothing is left un-finished
    assert all(s.t1 is not None for s in reqtrace.spans())


# -- the quantile sketch --------------------------------------------------

def test_sketch_within_one_bin_of_np_percentile():
    rng = np.random.default_rng(42)
    vals = np.exp(rng.normal(-6.0, 1.5, size=4096))   # ~ms latencies
    sk = series.QuantileSketch()
    for v in vals:
        sk.add(float(v))
    for q in (0.5, 0.95, 0.99):
        est = sk.quantile(q)
        ref = float(np.percentile(vals, q * 100))
        assert abs(series.bin_index(est) - series.bin_index(ref)) \
            <= 1, (q, est, ref)
        # one-bin accuracy == a bounded relative envelope
        assert est / ref < series.GAMMA ** 2
        assert ref / est < series.GAMMA ** 2
    assert sk.count == len(vals)
    assert sk.min == float(vals.min())
    assert sk.max == float(vals.max())
    assert abs(sk.sum - float(vals.sum())) < 1e-6 * sk.sum


def test_sketch_edge_cases():
    sk = series.QuantileSketch()
    assert sk.quantile(0.5) is None
    sk.add(0.0)                          # below V0: clamps to bin 0
    assert series.bin_index(0.0) == 0
    assert sk.quantile(0.5) is not None
    big = series.V0 * series.GAMMA ** (series.NBINS + 50)
    assert series.bin_index(big) == series.NBINS - 1


# -- SLO burn -> admission ------------------------------------------------

def _burn_tenant(name, n=20, factor=4.0):
    """Seed a tenant's SLO window with `n` violating latencies."""
    tgt = series.slo_target_s()
    for _ in range(n):
        series.note_slo(name, tgt * factor)


def test_slo_burn_accounting():
    series.enable()
    assert series.slo_burn("quiet") is None
    _burn_tenant("hot", n=10)
    series.note_slo("hot", 0.0)          # one good request
    b = series.slo_burn("hot")
    assert b["objective"] == "latency_ms<=%d" % round(
        series.slo_target_s() * 1e3)
    assert b["window"] == 11
    assert abs(b["burn"] - 10 / 11) < 1e-3


def test_slo_burn_sheds_lowest_priority_with_objective():
    """A tenant burning past serve/slo_burn_pct sheds at the lowest
    priority, and the escalation payload records WHICH objective was
    violated plus the active trace id."""
    series.enable()
    reqtrace.enable()
    oe.enable()
    _burn_tenant("bulk")
    with bq.CoalescingQueue(background=False) as q:
        ctrl = AdmissionController(
            q, tenants=[TenantConfig("bulk", priority="batch")])
        sp = reqtrace.begin(tenant="bulk", op="potrf")
        with reqtrace.active(sp):
            decision = ctrl.admit(ctrl.tenant("bulk"), "potrf",
                                  np.float32, 0)
    assert decision == SHED
    assert guard.counts()["resil.fallback.serve_shed"] == 1
    fb = [e for e in oe.events(cat="resil")
          if e.name == "resil::fallback"]
    assert len(fb) == 1
    args = fb[0].args
    assert args["rung"] == "serve_shed"
    assert args["objective"].startswith("latency_ms<=")
    assert args["burn"] == 1.0
    assert args["trace"] == sp.trace_id


def test_slo_burn_degrades_degradable_f64():
    """A burning standard-priority tenant with degradable f64 work is
    DEGRADED (served f32) rather than shed."""
    series.enable()
    oe.enable()
    _burn_tenant("std")
    with bq.CoalescingQueue(background=False) as q:
        ctrl = AdmissionController(q)
        decision = ctrl.admit(ctrl.tenant("std"), "posv",
                              np.float64, 0)
    assert decision == DEGRADE
    fb = [e for e in oe.events(cat="resil")
          if e.name == "resil::fallback"]
    assert fb[0].args["rung"] == "serve_degrade"
    assert fb[0].args["objective"].startswith("latency_ms<=")


def test_healthy_burn_admits():
    series.enable()
    series.note_slo("ok", 0.0)
    with bq.CoalescingQueue(background=False) as q:
        ctrl = AdmissionController(
            q, tenants=[TenantConfig("ok", priority="batch")])
        assert ctrl.admit(ctrl.tenant("ok"), "potrf",
                          np.float32, 0) == "admit"


def test_admit_record_carries_slo_pressure():
    """The serve.admit ledger record includes the slo_burn pressure
    input the decision was made from."""
    series.enable()
    oledger.enable()
    _burn_tenant("bulk")
    with bq.CoalescingQueue(background=False) as q:
        ctrl = AdmissionController(
            q, tenants=[TenantConfig("bulk", priority="batch")])
        ctrl.admit(ctrl.tenant("bulk"), "potrf", np.float32, 0)
    recs = oledger.records("serve.admit")
    assert recs and recs[-1].meta["decision"] == "shed"
    assert recs[-1].meta["slo_burn"]["burn"] == 1.0


# -- exposition -----------------------------------------------------------

def test_metrics_rpc_roundtrip():
    reqtrace.enable()
    series.enable()
    with _server() as srv, srpc.RpcServer(srv) as rs, \
            srpc.RpcClient(rs.address) as cl:
        assert "slate_" not in cl.metrics()   # nothing sampled yet
        cl.submit("potrf", _spd(16), tenant="acme")
        text = cl.metrics()
    assert '# TYPE slate_serve_latency_s summary' in text
    assert 'slate_serve_latency_s{tenant="acme",op="potrf",' \
        'quantile="0.95"}' in text
    assert 'slate_serve_latency_s_count{tenant="acme",op="potrf"} 1' \
        in text
    assert "slate_serve_slo_burn" in text


def test_metrics_rpc_off_state_empty():
    with _server() as srv, srpc.RpcServer(srv) as rs, \
            srpc.RpcClient(rs.address) as cl:
        assert cl.metrics() == ""


def test_report_serve_section():
    reqtrace.enable()
    series.enable()
    with _server() as srv:
        srv.submit("potrf", _spd(16), tenant="acme").result(
            timeout=60)
    snap = obs.snapshot()
    key = "serve.latency_s|acme|potrf"
    assert snap["serve_series"]["series"][key]["count"] == 1
    text = obs.report()
    assert "serving latency" in text
    assert "serve.latency_s" in text and "acme" in text


# -- Perfetto flow export -------------------------------------------------

def _phs(trace_obj):
    return {r["ph"] for r in trace_obj["traceEvents"]}


def test_export_flow_events_off_and_on():
    """Off: byte-identical export (no flow phases at all). On: every
    traced request gets a flow start on its serve::request span and a
    flow end on the batch::flush slice that carried it, joined by the
    trace_id."""
    from slate_tpu.obs.export import chrome_trace
    oe.enable()
    with _server() as srv:
        srv.submit("potrf", _spd(16)).result(timeout=60)
    off = chrome_trace()
    assert not ({"s", "f"} & _phs(off))
    oe.clear()
    reqtrace.enable()
    with _server() as srv:
        t = srv.submit("potrf", _spd(16))
        t.result(timeout=60)
    on = chrome_trace()
    flows = [r for r in on["traceEvents"]
             if r["name"] == "serve.flow"]
    assert {r["ph"] for r in flows} == {"s", "f"}
    tid = t.span.trace_id
    starts = [r for r in flows if r["ph"] == "s"]
    ends = [r for r in flows if r["ph"] == "f"]
    assert any(r["id"] == tid for r in starts)
    assert any(r["id"] == tid and r.get("bp") == "e" for r in ends)


def test_flush_timestamps_consistent_with_span_event():
    """The bus's serve::request event and the Span agree (one commit
    writes both)."""
    oe.enable()
    reqtrace.enable()
    with _server() as srv:
        t = srv.submit("potrf", _spd(16))
        t.result(timeout=60)
    evs = [e for e in oe.events(cat="serve")
           if e.name == reqtrace.REQUEST_SPAN]
    assert len(evs) == 1
    assert evs[0].args["trace_id"] == t.span.trace_id
    assert evs[0].t0 == t.span.t0 and evs[0].t1 == t.span.t1


# -- concurrency ----------------------------------------------------------

def test_concurrent_traced_submits_distinct_traces():
    reqtrace.enable()
    series.enable()
    results = {}

    def worker(i):
        with _server() as srv:
            t = srv.submit("potrf", _spd(16, seed=i),
                           tenant="t%d" % i)
            t.result(timeout=60)
            results[i] = t.span

    threads = [threading.Thread(target=worker, args=(i,))
               for i in range(4)]
    for th in threads:
        th.start()
    for th in threads:
        th.join()
    tids = {sp.trace_id for sp in results.values()}
    assert len(tids) == 4
    for i, sp in results.items():
        assert sp.tenant == "t%d" % i
        assert sp.t1 is not None and "flush_id" in sp.args


def test_ring_bounded_and_drop_counted(monkeypatch):
    monkeypatch.setattr(reqtrace, "SPAN_CAP", 8)
    monkeypatch.setattr(reqtrace, "_spans",
                        collections.deque(maxlen=8))
    reqtrace.enable()
    for i in range(12):
        reqtrace.begin(tenant="t", op="o").finish()
    assert reqtrace.count() == 8
    assert reqtrace.dropped() == 4
