"""Native layout engine + LAPACK/ScaLAPACK import-export tests
(reference unit_test/test_Matrix.cc fromLAPACK/fromScaLAPACK coverage;
scalapack_api round trips)."""

import numpy as np
import pytest

from slate_tpu import native
from slate_tpu.core import io


def test_native_lib_loads():
    lib = native.get_lib()
    assert lib is not None, "C++ layout engine failed to build/load"
    assert lib.slate_tpu_native_abi_version() == 1


@pytest.mark.parametrize("dtype", [np.float32, np.float64])
def test_pack_unpack_roundtrip(rng, dtype):
    a = np.asfortranarray(rng.standard_normal((100, 70)).astype(dtype))
    packed = native.pack_colmajor(a, 128, 80)
    assert packed.shape == (128, 80)
    np.testing.assert_array_equal(packed[:100, :70], a)
    assert np.all(packed[100:] == 0) and np.all(packed[:, 70:] == 0)
    back = native.unpack_colmajor(packed, 100, 70)
    np.testing.assert_array_equal(back, a)
    assert back.flags.f_contiguous


def test_pack_matches_numpy_fallback(rng):
    a = np.asfortranarray(rng.standard_normal((33, 17)))
    fast = native.pack_colmajor(a, 48, 32)
    slow = np.zeros((48, 32))
    slow[:33, :17] = a
    np.testing.assert_array_equal(fast, slow)


def test_from_to_lapack(rng):
    a = np.asfortranarray(rng.standard_normal((50, 30)))
    A = io.fromLAPACK(a, mb=16)
    np.testing.assert_allclose(A.to_numpy(), a)
    back = io.toLAPACK(A)
    np.testing.assert_allclose(back, a)


def test_scalapack_roundtrip(rng):
    m, n, mb, nb, p, q = 70, 50, 16, 16, 2, 2
    a = rng.standard_normal((m, n))
    A = io.fromLAPACK(np.asfortranarray(a), mb=mb, nb=nb)
    locals_ = io.toScaLAPACK(A, p, q)
    assert len(locals_) == p * q
    B = io.fromScaLAPACK(
        [(pi, qi, arr) for (pi, qi), arr in locals_.items()],
        m, n, mb, nb, p, q)
    np.testing.assert_allclose(B.to_numpy(), a)


def test_scalapack_locals_shape(rng):
    # 4 tiles x 3 tiles on a 2x2 grid: rank (0,0) owns tile rows {0,2},
    # tile cols {0,2}
    m, n, mb, nb = 64, 48, 16, 16
    a = rng.standard_normal((m, n))
    A = io.fromLAPACK(np.asfortranarray(a), mb=mb, nb=nb)
    locals_ = io.toScaLAPACK(A, 2, 2)
    l00 = locals_[(0, 0)]
    assert l00.shape == (32, 32)
    np.testing.assert_allclose(l00[:16, :16], a[0:16, 0:16])
    np.testing.assert_allclose(l00[16:, :16], a[32:48, 0:16])
