"""Divide & conquer tridiagonal eigensolver tests (reference
test/test_stedc.cc role)."""

import numpy as np
import pytest

import slate_tpu as st


def tridiag_eig_np(d, e):
    t = np.diag(d) + np.diag(e, -1) + np.diag(e, 1)
    return np.linalg.eigh(t)


@pytest.mark.parametrize("n", [16, 64, 100])
def test_stedc_solve(rng, n):
    d = rng.standard_normal(n)
    e = rng.standard_normal(n - 1)
    w, v = st.stedc_solve(d, e)
    wn, vn = tridiag_eig_np(d, e)
    np.testing.assert_allclose(np.asarray(w), wn, rtol=1e-9, atol=1e-10)
    v = np.asarray(v)
    # residual + orthogonality
    t = np.diag(d) + np.diag(e, -1) + np.diag(e, 1)
    assert np.abs(t @ v - v * np.asarray(w)[None, :]).max() < 1e-9
    assert np.abs(v.T @ v - np.eye(n)).max() < 1e-8


def test_stedc_with_backtransform(rng):
    n = 48
    a = rng.standard_normal((n, n))
    a = (a + a.T) / 2
    A = st.HermitianMatrix(st.Uplo.Lower, a, mb=16)
    Band, Q = st.he2hb(A)
    tri = st.hb2st(Band)
    Qfull = st.unmtr_he2hb(Q, tri.Q) if tri.Q is not None else Q
    w, V = st.stedc(tri.d, tri.e, Qfull)
    v = V.to_numpy()
    np.testing.assert_allclose(np.asarray(w), np.linalg.eigvalsh(a),
                               rtol=1e-8, atol=1e-9)
    assert np.abs(a @ v - v * np.asarray(w)[None, :]).max() < 1e-7


def test_stedc_deflation_path(rng):
    # decoupled problem: rho = 0 exactly
    n = 32
    d = np.sort(rng.standard_normal(n))
    e = rng.standard_normal(n - 1) * 0.1
    e[n // 2 - 1] = 0.0
    w, v = st.stedc_solve(d, e)
    wn, _ = tridiag_eig_np(d, e)
    np.testing.assert_allclose(np.asarray(w), wn, rtol=1e-9, atol=1e-10)


def test_secular_phase_direct(rng):
    import jax.numpy as jnp
    n = 24
    D = np.sort(rng.standard_normal(n))
    z = rng.standard_normal(n) / np.sqrt(n)
    rho = 0.7
    defl = st.stedc_deflate(jnp.asarray(D), jnp.asarray(z), rho)
    lam, U = st.stedc_secular(defl.d, defl.z, rho, defl.keep)
    M = np.diag(D) + rho * np.outer(z, z)
    wn = np.linalg.eigvalsh(M)
    np.testing.assert_allclose(np.sort(np.asarray(lam)), wn, rtol=1e-8,
                               atol=1e-9)
    # eigenvectors of the secular system (incl. deflation rotations)
    Q = st.stedc_rotate(jnp.eye(n), defl)
    V = np.asarray(Q) @ np.asarray(U)
    assert np.abs(M @ V - V * np.asarray(lam)[None, :]).max() < 1e-10
    assert np.abs(V.T @ V - np.eye(n)).max() < 1e-10


def test_secular_negative_rho(rng):
    import jax.numpy as jnp
    n = 24
    D = np.sort(rng.standard_normal(n))
    z = rng.standard_normal(n) / np.sqrt(n)
    rho = -0.6
    defl = st.stedc_deflate(jnp.asarray(D), jnp.asarray(z), rho)
    lam, U = st.stedc_secular(defl.d, defl.z, rho, defl.keep)
    M = np.diag(D) + rho * np.outer(z, z)
    wn = np.linalg.eigvalsh(M)
    np.testing.assert_allclose(np.sort(np.asarray(lam)), wn, rtol=1e-8,
                               atol=1e-9)
    # eigenvector coverage of the rho<0 origin-selection branch
    Q = st.stedc_rotate(jnp.eye(n), defl)
    V = np.asarray(Q) @ np.asarray(U)
    lamn = np.asarray(lam)
    assert np.abs(M @ V - V * lamn[None, :]).max() < 1e-10
    assert np.abs(V.T @ V - np.eye(n)).max() < 1e-10


def test_merge_decoupled_above_leaf(rng):
    """rho == 0 at the split point with n > leaf: the merge must return
    the concatenated sub-results exactly (round-1 ADVICE finding: the
    old rho-floor path produced 0.32 absolute error here)."""
    n = 64
    d = rng.standard_normal(n)
    e = rng.standard_normal(n - 1) * 0.5
    e[n // 2 - 1] = 0.0
    w, v = st.stedc_solve(d, e)
    wn, _ = tridiag_eig_np(d, e)
    np.testing.assert_allclose(np.asarray(w), wn, rtol=1e-9, atol=1e-10)
    t = np.diag(d) + np.diag(e, -1) + np.diag(e, 1)
    v = np.asarray(v)
    assert np.abs(t @ v - v * np.asarray(w)[None, :]).max() < 1e-9


def test_stedc_clustered_eigenvalues(rng):
    """Near-tied poles exercise the Givens tie-rotation deflation."""
    n = 60
    d = np.repeat(np.sort(rng.standard_normal(n // 4)), 4)
    e = np.full(n - 1, 1e-12)
    w, v = st.stedc_solve(d, e)
    wn, _ = tridiag_eig_np(d, e)
    np.testing.assert_allclose(np.asarray(w), wn, rtol=1e-9, atol=1e-10)
    t = np.diag(d) + np.diag(e, -1) + np.diag(e, 1)
    v = np.asarray(v)
    assert np.abs(t @ v - v * np.asarray(w)[None, :]).max() < 1e-9
    assert np.abs(v.T @ v - np.eye(n)).max() < 1e-8


def test_rotation_matrix_matches_column_loop(rng):
    """The composed rotation matrix (one matmul) must reproduce the
    column-at-a-time rotation application exactly, including cases
    with many ties (chained rotations) and tiny-z deflations."""
    import jax.numpy as jnp
    from slate_tpu.linalg.stedc import _stedc_rotate_cols

    n = 40
    # force heavy deflation: clustered poles + some tiny z entries
    D = np.sort(np.repeat(rng.standard_normal(n // 4), 4)
                + 1e-14 * rng.standard_normal(n))
    z = rng.standard_normal(n) / np.sqrt(n)
    z[::5] = 1e-18
    for rho in (0.9, -0.8):
        defl = st.stedc_deflate(jnp.asarray(D), jnp.asarray(z), rho)
        Q = jnp.asarray(rng.standard_normal((n, n)))
        ref = np.asarray(_stedc_rotate_cols(Q, defl))
        got = np.asarray(st.stedc_rotate(Q, defl))
        np.testing.assert_allclose(got, ref, rtol=1e-12, atol=1e-13)


def test_fused_deflate_rotation_matches_separate(rng):
    """stedc_merge's production path is the FUSED deflation+rotation
    scan (_deflate_rotation_fused); it must stay bit-identical to the
    separate stedc_deflate + stedc_rotation_matrix pair it replaced —
    the fusion relies on the subtle shared-partner-chain invariant
    (keep[nj] == keep0[nj] inside the scan), so equivalence is pinned
    here across ties, tiny-z deflation, both rho signs, and rho=0."""
    import jax.numpy as jnp
    from slate_tpu.linalg.stedc import (_deflate_rotation_fused,
                                        stedc_rotation_matrix)

    n = 40
    for trial in range(6):
        r = np.random.default_rng(100 + trial)
        if trial % 2:
            D = np.sort(np.repeat(r.standard_normal(n // 4), 4)
                        + 1e-14 * r.standard_normal(n))
        else:
            D = np.sort(r.standard_normal(n))
        z = r.standard_normal(n) / np.sqrt(n)
        z[::5] = 1e-18
        for rho in (0.9, -0.8, 0.0):
            Dj, zj = jnp.asarray(D), jnp.asarray(z)
            ref = st.stedc_deflate(Dj, zj, rho)
            Gref = np.asarray(stedc_rotation_matrix(ref))
            defl, G = _deflate_rotation_fused(Dj, zj, rho)
            for a, b in zip(defl, ref):
                np.testing.assert_array_equal(np.asarray(a),
                                              np.asarray(b))
            np.testing.assert_array_equal(np.asarray(G), Gref)


def test_stedc_solve_padded_driver(rng):
    """Non-power-of-two n exercises the sentinel-padded level-by-level
    driver: results must match eigh, sentinels must not leak."""
    for n in (100, 129):
        d = rng.standard_normal(n)
        e = rng.standard_normal(n - 1)
        w, v = st.stedc_solve(d, e, leaf=16)
        t = np.diag(d) + np.diag(e, -1) + np.diag(e, 1)
        wn = np.linalg.eigvalsh(t)
        np.testing.assert_allclose(np.asarray(w), wn, rtol=1e-9,
                                   atol=1e-9)
        vn = np.asarray(v)
        assert vn.shape == (n, n)
        assert np.abs(t @ vn - vn * np.asarray(w)[None, :]).max() < 1e-8
        assert np.abs(vn.T @ vn - np.eye(n)).max() < 1e-8


def test_stedc_solve_scale_invariant(rng):
    """Sentinel padding must scale with the spectrum: a 1e-10-scale
    matrix keeps relative accuracy (review regression: absolute
    sentinel offsets inflated the deflation tolerance and falsely
    deflated the whole spectrum)."""
    n = 70
    d = rng.standard_normal(n) * 1e-10
    e = rng.standard_normal(n - 1) * 1e-10
    w, v = st.stedc_solve(d, e, leaf=16)
    t = np.diag(d) + np.diag(e, -1) + np.diag(e, 1)
    wn = np.linalg.eigvalsh(t)
    np.testing.assert_allclose(np.asarray(w), wn, rtol=1e-9,
                               atol=1e-12 * np.abs(wn).max())
    vn = np.asarray(v)
    assert (np.abs(t @ vn - vn * np.asarray(w)[None, :]).max()
            < 1e-8 * np.abs(wn).max())


def test_steqr2_values_only_and_vectors(rng):
    """steqr2 values-only path avoids the dense n x n embed
    (eigh_tridiagonal on the vectors); vector path delegates to D&C."""
    n = 48
    d = rng.standard_normal(n)
    e = rng.standard_normal(n - 1)
    t = np.diag(d) + np.diag(e, -1) + np.diag(e, 1)
    wn = np.linalg.eigvalsh(t)
    w, v = st.steqr2(d, e, want_vectors=False)
    assert v is None
    np.testing.assert_allclose(np.asarray(w), wn, rtol=1e-9, atol=1e-9)
    w2, v2 = st.steqr2(d, e)
    np.testing.assert_allclose(np.asarray(w2), wn, rtol=1e-9, atol=1e-9)
    vn = np.asarray(v2)
    assert np.abs(t @ vn - vn * np.asarray(w2)[None, :]).max() < 1e-8
