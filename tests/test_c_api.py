"""C API tests (reference unit_test/test_c_api.cc role): compile a real
C program against slate_c.h, link libslate_tpu_c.so, run it as a
subprocess and check its numerical output — proving a C caller can use
the framework end to end without touching Python."""

import os
import pathlib
import subprocess
import sys

import numpy as np
import pytest

from slate_tpu import c_api

C_MAIN = r"""
#include <stdio.h>
#include <stdlib.h>
#include <math.h>
#include "slate_c.h"

int main(void) {
    if (slate_tpu_init("cpu") != 0) { printf("INIT FAIL\n"); return 1; }
    enum { N = 24, NRHS = 2 };
    double a[N * N], acpy[N * N], b[N * NRHS], x[N * NRHS];
    /* SPD matrix: diag-dominant symmetric */
    srand(7);
    for (int i = 0; i < N; i++)
        for (int j = 0; j <= i; j++) {
            double v = (double)rand() / RAND_MAX - 0.5;
            a[i * N + j] = v; a[j * N + i] = v;
        }
    for (int i = 0; i < N; i++) a[i * N + i] += N;
    for (int i = 0; i < N * N; i++) acpy[i] = a[i];
    for (int i = 0; i < N * NRHS; i++) { b[i] = (double)rand() / RAND_MAX; x[i] = b[i]; }

    int info = slate_posv('d', N, NRHS, a, N, x, NRHS);
    if (info != 0) { printf("POSV INFO %d\n", info); return 1; }
    /* residual check in C */
    double maxres = 0;
    for (int i = 0; i < N; i++)
        for (int r = 0; r < NRHS; r++) {
            double s = 0;
            for (int j = 0; j < N; j++) s += acpy[i * N + j] * x[j * NRHS + r];
            double d = fabs(s - b[i * NRHS + r]);
            if (d > maxres) maxres = d;
        }
    printf("POSV RESID %.3e\n", maxres);
    if (maxres > 1e-8) return 1;

    /* gesv on a general system */
    double g[N * N];
    int32_t ipiv[N];
    for (int i = 0; i < N * N; i++) g[i] = (double)rand() / RAND_MAX - 0.5;
    for (int i = 0; i < N; i++) g[i * N + i] += N;
    double gcpy[N * N]; for (int i = 0; i < N * N; i++) gcpy[i] = g[i];
    for (int i = 0; i < N * NRHS; i++) x[i] = b[i];
    info = slate_gesv('d', N, NRHS, g, N, ipiv, x, NRHS);
    if (info != 0) { printf("GESV INFO %d\n", info); return 1; }
    maxres = 0;
    for (int i = 0; i < N; i++)
        for (int r = 0; r < NRHS; r++) {
            double s = 0;
            for (int j = 0; j < N; j++) s += gcpy[i * N + j] * x[j * NRHS + r];
            double d = fabs(s - b[i * NRHS + r]);
            if (d > maxres) maxres = d;
        }
    printf("GESV RESID %.3e\n", maxres);
    if (maxres > 1e-8) return 1;

    /* non-SPD must report info > 0, not crash */
    for (int i = 0; i < N * N; i++) a[i] = acpy[i];
    a[5 * N + 5] = -1000.0;
    for (int i = 0; i < N * NRHS; i++) x[i] = b[i];
    info = slate_posv('d', N, NRHS, a, N, x, NRHS);
    printf("NONSPD INFO %d\n", info);
    if (info <= 0) return 1;

    printf("C API OK\n");
    return 0;
}
"""


@pytest.fixture(scope="module")
def c_program(tmp_path_factory):
    so = c_api.build_library()
    if so is None:
        pytest.skip("no C toolchain / libpython for embedding")
    tmp = tmp_path_factory.mktemp("c_api")
    src = tmp / "main.c"
    src.write_text(C_MAIN)
    exe = tmp / "c_demo"
    subprocess.run(
        ["gcc", "-O1", str(src), "-o", str(exe),
         f"-I{c_api.HEADER.parent}", str(so),
         f"-Wl,-rpath,{so.parent}", "-lm"],
        check=True, capture_output=True, timeout=180)
    return exe


def test_c_program_end_to_end(c_program):
    env = dict(os.environ)
    env["JAX_PLATFORMS"] = "cpu"
    # the embedded interpreter must find the repo's packages
    env["PYTHONPATH"] = str(pathlib.Path(__file__).parent.parent) \
        + os.pathsep + env.get("PYTHONPATH", "")
    out = subprocess.run([str(c_program)], env=env, capture_output=True,
                         text=True, timeout=600)
    assert out.returncode == 0, f"stdout:{out.stdout}\nstderr:{out.stderr}"
    assert "C API OK" in out.stdout
    assert "NONSPD INFO 6" in out.stdout     # exact failing minor (k=5 -> info 6)
