"""obs/ subsystem tests (ISSUE 3): the unified event bus + zero-cost
disabled path, Perfetto JSON round trip, compiled-HLO collective
counts against the dist/ tree schedule, the recompile detector, the
trace SVG satellites (XML escaping, cross-thread merge), and the
tune-stats snapshot aliasing fix."""

import dataclasses
import json
import threading

import jax
import jax.numpy as jnp
import numpy as np
import pytest

import slate_tpu as st
from slate_tpu import TiledMatrix, obs
from slate_tpu.core.methods import MethodEig, MethodFactor
from slate_tpu.core.options import Option
from slate_tpu.obs import events as obs_events
from slate_tpu.obs import metrics as obs_metrics
from slate_tpu.obs import xprof
from slate_tpu.utils import trace


@pytest.fixture
def obs_clean():
    """Fresh, disabled observability state around each test."""
    obs.disable()
    obs_events.clear()
    obs_metrics.reset()
    xprof.clear_analyses()
    yield
    obs.disable()
    obs_events.clear()
    obs_metrics.reset()
    xprof.clear_analyses()


def _spd(rng, n):
    a = rng.standard_normal((n, n))
    return a @ a.T + n * np.eye(n)


def dist_opts(grid):
    return {Option.Grid: grid, Option.MethodFactor: MethodFactor.Tiled}


def shard(grid, A):
    return dataclasses.replace(
        A, data=jax.device_put(A.data, grid.matrix_sharding()))


# -- bus ------------------------------------------------------------------

def test_disabled_path_records_nothing(rng, obs_clean):
    """The zero-cost contract: with observability off, a fully
    instrumented driver leaves no events and no counters."""
    A = st.HermitianMatrix(st.Uplo.Lower, _spd(rng, 16), mb=8)
    st.potrf(A)
    with trace.block("not-recorded"):
        pass
    trace.mark("also-not-recorded")
    assert obs.bus_events() == []
    snap = obs.snapshot()
    assert snap["metrics"]["counters"] == {}
    assert snap["drivers"] == {}


def test_bus_merges_sources_and_threads(rng, obs_clean):
    """trace blocks, tuner-style marks, driver spans and off-thread
    events all land in ONE stream (the satellite-2 fix: the old
    thread-local buffer dropped worker-thread events)."""
    obs.enable()
    A = st.HermitianMatrix(st.Uplo.Lower, _spd(rng, 16), mb=8)
    st.potrf(A)                                  # driver span
    with trace.block("host::stage"):             # trace block
        pass
    trace.mark("tune::fake=1 [frozen]")          # tuner mark

    def worker():
        with trace.block("ooc::off-thread"):
            pass

    t = threading.Thread(target=worker, name="stager")
    t.start()
    t.join()
    evs = obs.bus_events()
    names = {e.name for e in evs}
    assert {"potrf", "host::stage", "tune::fake=1 [frozen]",
            "ooc::off-thread"} <= names
    tids = {e.tid for e in evs}
    assert len(tids) == 2                        # main + worker
    # the off-thread block is visible to finish() too
    svg = trace.finish()
    assert "ooc::off-thread" in svg
    # finish drains ONLY the legacy trace categories; the obs
    # session's driver spans survive for the Perfetto export
    left = obs.bus_events()
    assert not [e for e in left
                if e.cat in ("trace", "phase", "tune")]
    assert [e for e in left if e.cat == "driver"]


def test_phases_publish_without_timers_option(rng, obs_clean):
    """trace.phases(opts) publishes phase spans to the bus with no
    Option.Timers plumbing — and still feeds a Timers instance when
    one is passed."""
    obs.enable()
    A = st.HermitianMatrix(st.Uplo.Lower, _spd(rng, 16), mb=8)
    B = TiledMatrix.from_dense(np.ones((16, 2)), 8)
    st.posv(A, B)
    phase_names = {e.name for e in obs.bus_events()
                   if e.cat == "phase"}
    assert {"posv::potrf", "posv::potrs"} <= phase_names
    tm = st.Timers()
    st.posv(A, B, {Option.Timers: tm})
    assert "posv::potrf" in tm.values


# -- Perfetto export ------------------------------------------------------

def test_perfetto_roundtrip(rng, obs_clean, tmp_path):
    """chrome_trace() must round-trip through json with the required
    ph/ts/name keys on every record, span durations in microseconds,
    and thread-name metadata."""
    obs.enable()
    A = st.HermitianMatrix(st.Uplo.Lower, _spd(rng, 16), mb=8)
    st.potrf(A)
    with obs.span("custom", cat="trace", detail=7):
        pass
    obs.counter("queue_depth", 3)
    path = obs.write_trace(str(tmp_path / "run.trace.json"))
    back = json.loads(open(path).read())
    evs = back["traceEvents"]
    assert evs, "no events exported"
    for rec in evs:
        assert {"ph", "ts", "name"} <= set(rec), rec
        assert "pid" in rec and "tid" in rec
    spans = [r for r in evs if r["ph"] == "X"]
    assert spans and all(r["dur"] >= 0 for r in spans)
    assert any(r["ph"] == "C" for r in evs)          # counter sample
    assert any(r["ph"] == "M" for r in evs)          # thread names
    assert any(r.get("args", {}).get("detail") == 7 for r in spans)


# -- recompile detector ---------------------------------------------------

def test_recompile_detector(rng, obs_clean):
    """Fires on a shape change, stays silent on a cache hit (the
    driver body never re-enters Python on a hit, so a second trace at
    a NEW (shape, dtype) key is exactly a recompile)."""
    obs.enable()
    A16 = st.HermitianMatrix(st.Uplo.Lower, _spd(rng, 16), mb=8)
    A24 = st.HermitianMatrix(st.Uplo.Lower, _spd(rng, 24), mb=8)

    def run(A):
        return jax.jit(
            lambda d: st.potrf(dataclasses.replace(A, data=d)).data
        )(jnp.asarray(A.data))

    run(A16)
    assert obs_metrics.recompiles() == 0          # first compile
    run(A16)
    assert obs_metrics.recompiles() == 0          # cache hit: silent
    run(A24)
    assert obs_metrics.recompiles() == 1          # shape change: fires
    assert any(e.name == "recompile:potrf"
               for e in obs.bus_events(cat="jit"))


# -- xprof ----------------------------------------------------------------

def test_xprof_potrf_attribution(rng, obs_clean):
    """analyze(): analytic FLOPs and peak memory from the compiler
    cost model, compile-vs-execute wall split, zero collectives on a
    single device — and obs.report() renders all of it."""
    obs.enable()
    n = 32
    A = st.HermitianMatrix(st.Uplo.Lower, _spd(rng, n), mb=8)

    @jax.jit
    def f(d):
        return st.potrf(dataclasses.replace(A, data=d)).data

    rec = obs.analyze("potrf", f, jnp.asarray(A.data))
    assert rec["flops"] > 0
    assert rec["peak_bytes"] > 0
    assert rec["compile_seconds"] > 0
    assert rec["execute_seconds"] >= 0
    assert rec["collectives"]["total"] == 0
    text = obs.report()
    assert "potrf" in text and "flops" in text
    assert "compile" in text and "execute" in text
    assert "collectives    none" in text


def test_collective_counts_parser():
    hlo = """
  %a = f32[8]{0} collective-permute(%x), source_target_pairs={{0,1}}
  %b = f32[8]{0} all-reduce(%x), to_apply=%sum
  %c = (f32[8], f32[8]) collective-permute-start(%x)
  %d = f32[8]{0} collective-permute-done(%c)
  %e = f32[8]{0} all-gather(%x), dimensions={0}
"""
    counts = obs.collective_counts(hlo)
    # the start/done async pair counts ONCE
    assert counts["collective-permute"] == 2
    assert counts["all-reduce"] == 1
    assert counts["all-gather"] == 1
    assert counts["reduce-scatter"] == 0
    assert counts["total"] == 4


def test_hlo_collectives_match_tree_schedule(rng, grid8, obs_clean):
    """The library form of test_dist.py's ad-hoc HLO assertion: the
    compiled gels_tsqr program contains EXACTLY the ppermutes the
    dist/tree.py schedule issues (schedule_ppermutes), and the driver
    publishes the same number to the comms accounting at trace time."""
    from slate_tpu.dist.tree import schedule_ppermutes
    obs.enable()
    m, n = 96, 8
    a = rng.standard_normal((m, n))
    b = rng.standard_normal((m, 2))
    As = shard(grid8, TiledMatrix.from_dense(a, 8))
    Bs = shard(grid8, TiledMatrix.from_dense(b, 8))

    @jax.jit
    def step(A, B):
        return st.gels_tsqr(A, B, dist_opts(grid8)).data

    expected = schedule_ppermutes(8, 2)          # frozen fanin=2 tree
    assert expected == 3                         # 8 devices, binary
    rec = obs.analyze("gels_tsqr_grid", step, As, Bs, run=False)
    assert rec["collectives"]["collective-permute"] == expected
    assert rec["flops"] > 0 and rec["peak_bytes"] > 0
    # trace-time comms accounting recorded the same schedule
    comms = [e for e in obs.bus_events(cat="comms")
             if e.name == "comms:tsqr_qt"]
    assert comms and comms[-1].args["ppermutes"] == expected
    snap = obs.snapshot()
    assert snap["metrics"]["counters"][
        "comms.ppermute.scheduled"] == expected
    # the acceptance surface: the report shows the matching count
    text = obs.report()
    assert "gels_tsqr_grid" in text
    assert "collective-permute=%d" % expected in text


def test_heev_dc_mesh_report_shows_collectives(rng, grid8, obs_clean):
    """Acceptance: grid heev(DC) analyzed end-to-end shows a nonzero
    collective count in obs.report() (the distributed stedc/back-
    transform resharding), next to FLOPs and peak memory."""
    obs.enable()
    n = 64
    a = rng.standard_normal((n, n))
    a = (a + a.T) / 2
    A1 = st.HermitianMatrix(st.Uplo.Lower, a, mb=8)
    opts = dict(dist_opts(grid8))
    opts[Option.MethodEig] = MethodEig.DC
    As = shard(grid8, A1)

    @jax.jit
    def step(d):
        w, V = st.heev(dataclasses.replace(As, data=d), opts)
        return w, V.data

    rec = obs.analyze("heev_dc_grid", step, As.data)
    assert rec["flops"] > 0 and rec["peak_bytes"] > 0
    assert rec["collectives"]["total"] > 0
    text = obs.report()
    assert "heev_dc_grid" in text
    assert "collectives    " in text and "=" in text.split(
        "collectives    ")[1].split("\n")[0]


# -- metrics wiring -------------------------------------------------------

def test_refine_and_ooc_metrics(rng, obs_clean):
    """Eager gesv_mixed records refine sweep counts; potrf_ooc records
    staging bytes and a driver span (off-thread D2H chunks ride the
    shared bus)."""
    obs.enable()
    n = 32
    a = rng.standard_normal((n, n)) + n * np.eye(n)
    b = rng.standard_normal((n, 2))
    st.gesv_mixed(st.Matrix(a, mb=8), TiledMatrix.from_dense(b, 8))
    snap = obs.snapshot()
    c = snap["metrics"]["counters"]
    assert c.get("refine.ir.calls") == 1
    assert "refine.ir.iters" in snap["metrics"]["histograms"]

    from slate_tpu.linalg.ooc import potrf_ooc
    spd = np.asarray(_spd(rng, 64), np.float64)
    L = potrf_ooc(spd, panel_cols=32)
    np.testing.assert_allclose(np.tril(L) @ np.tril(L).T, spd,
                               atol=1e-8)
    snap = obs.snapshot()
    c = snap["metrics"]["counters"]
    assert c.get("ooc.h2d_bytes", 0) > 0
    assert c.get("ooc.d2h_bytes", 0) > 0
    assert snap["drivers"]["potrf_ooc"]["calls"] == 1


# -- trace satellites -----------------------------------------------------

def test_trace_svg_escapes_xml(obs_clean, tmp_path):
    """Satellite 1: tuner marks legitimately contain <>& (e.g.
    \"tune::eig.method=<MethodEig.DC: 'dc'> [frozen]\") and must not
    produce malformed SVG."""
    import xml.dom.minidom
    obs.enable()
    trace.mark("tune::eig.method=<MethodEig.DC: 'dc'> [frozen]")
    with trace.block("a & b <gemm>"):
        pass
    svg = trace.finish(str(tmp_path / "t.svg"))
    assert "&lt;MethodEig.DC" in svg
    assert "a &amp; b &lt;gemm&gt;" in svg
    xml.dom.minidom.parseString(svg)     # parses = well-formed


def test_tune_stats_snapshot_is_deep_copy():
    """Satellite 3: mutating a snapshot's `recent` entries must not
    reach the live ring."""
    from slate_tpu.tune import stats
    stats.reset()
    stats.record_decision("op", "param", "frozen", 42)
    snap = stats.snapshot()
    snap["recent"][0]["value"] = "CORRUPTED"
    snap2 = stats.snapshot()
    assert snap2["recent"][0]["value"] == repr(42)
    stats.reset()
