"""Out-of-core streaming drivers (linalg/ooc.py): the streamed panel
schedule must reproduce the in-core results exactly up to roundoff,
with HBM residency bounded by one panel (exercised here with panels
much smaller than the matrix, so every code path — multi-visit
left-looking updates, ragged last panel — runs)."""

import numpy as np
import pytest

import slate_tpu as st
from slate_tpu.linalg.ooc import gemm_ooc, potrf_ooc


@pytest.fixture
def rng():
    return np.random.default_rng(1234)


def test_potrf_ooc_matches_incore(rng):
    n = 384
    x = rng.standard_normal((n, n))
    a = x @ x.T / n + 4.0 * np.eye(n)
    L = potrf_ooc(a, panel_cols=128)
    r = a - L @ L.T
    assert np.abs(r).max() / np.abs(a).max() < 1e-12
    assert np.allclose(L, np.tril(L))


def test_potrf_ooc_ragged_panel(rng):
    n = 300                       # 300 = 2*128 + 44: ragged last panel
    x = rng.standard_normal((n, n))
    a = x @ x.T / n + 4.0 * np.eye(n)
    L = potrf_ooc(a, panel_cols=128)
    ref = np.linalg.cholesky(a)
    assert np.abs(L - ref).max() < 1e-10


def test_potrf_ooc_single_panel(rng):
    n = 64
    x = rng.standard_normal((n, n))
    a = x @ x.T / n + 2.0 * np.eye(n)
    L = potrf_ooc(a, panel_cols=256)      # whole matrix in one panel
    assert np.abs(a - L @ L.T).max() < 1e-12


def test_getrf_ooc_matches_incore(rng):
    """Streamed left-looking LU must match the in-core factorization
    up to roundoff: same pivots, residual-exact solve."""
    from slate_tpu.linalg.ooc import getrf_ooc, getrs_ooc
    n = 384
    a = rng.standard_normal((n, n)) + 0.2 * n * np.eye(n)
    lu, ipiv = getrf_ooc(a, panel_cols=128)
    # P A = L U reconstruction
    L = np.tril(lu, -1) + np.eye(n)
    U = np.triu(lu)
    from slate_tpu.linalg.ooc import _swaps_to_perm
    perm = _swaps_to_perm(ipiv, n)
    assert np.abs(a[perm] - L @ U).max() / np.abs(a).max() < 1e-12
    # streamed solve
    b = rng.standard_normal((n, 3))
    x = getrs_ooc(lu, ipiv, b, panel_cols=128)
    assert np.abs(a @ x - b).max() < 1e-9


def test_getrf_ooc_matches_incore_pivots(rng):
    """Panel-confined pivoting sees exactly the rows in-core partial
    pivoting would search, so the pivot SEQUENCE matches the in-core
    driver's."""
    from slate_tpu.linalg.ooc import getrf_ooc
    n = 256
    a = rng.standard_normal((n, n))
    lu, ipiv = getrf_ooc(a, panel_cols=64)
    F = st.getrf(st.Matrix(a, mb=64))
    np.testing.assert_array_equal(ipiv, np.asarray(F.pivots)[:n])
    np.testing.assert_allclose(lu, np.asarray(F.LU.to_numpy()),
                               rtol=1e-10, atol=1e-12)


def test_getrf_ooc_ragged_and_rect(rng):
    from slate_tpu.linalg.ooc import getrf_ooc, _swaps_to_perm
    # ragged last panel
    n = 300
    a = rng.standard_normal((n, n))
    lu, ipiv = getrf_ooc(a, panel_cols=128)
    L = np.tril(lu, -1) + np.eye(n)
    perm = _swaps_to_perm(ipiv, n)
    assert np.abs(a[perm] - L @ np.triu(lu)).max() < 1e-10
    # wide rectangle (kmax inside a panel)
    m, n2 = 160, 300
    a2 = rng.standard_normal((m, n2))
    lu2, ipiv2 = getrf_ooc(a2, panel_cols=128)
    L2 = np.tril(lu2[:, :m], -1) + np.eye(m)
    perm2 = _swaps_to_perm(ipiv2, m)
    assert np.abs(a2[perm2] - L2 @ np.triu(lu2)).max() < 1e-10
    # tall rectangle
    m3, n3 = 300, 160
    a3 = rng.standard_normal((m3, n3))
    lu3, ipiv3 = getrf_ooc(a3, panel_cols=128)
    L3 = np.tril(lu3, -1)[:, :n3] + np.eye(m3, n3)
    perm3 = _swaps_to_perm(ipiv3, m3)
    assert np.abs(a3[perm3] - L3 @ np.triu(lu3[:n3])).max() < 1e-10


def test_geqrf_ooc_matches_incore(rng):
    """Streamed left-looking QR: packed factor reconstructs A and
    matches the in-core geqrf driver's R up to sign."""
    from slate_tpu.linalg.ooc import geqrf_ooc, unmqr_ooc
    m, n = 384, 384
    a = rng.standard_normal((m, n))
    qr_p, taus = geqrf_ooc(a, panel_cols=128)
    # Q (R-embedded) reconstruction: A == Q R
    R = np.triu(qr_p)[:n]
    QR = unmqr_ooc(qr_p, taus, np.vstack([R, np.zeros((m - n, n))]),
                   trans=False, panel_cols=128)
    assert np.abs(QR - a).max() / np.abs(a).max() < 1e-12
    # R matches in-core geqrf's R up to column signs
    F = st.geqrf(st.Matrix(a, mb=128))
    R_ref = np.triu(np.asarray(F.QR.to_numpy()))[:n]
    s = np.sign(np.diag(R)) * np.sign(np.diag(R_ref))
    assert np.abs(R - s[:, None] * R_ref).max() < 1e-9


def test_gels_ooc_tall_skinny(rng):
    from slate_tpu.linalg.ooc import gels_ooc
    m, n, nrhs = 500, 96, 2
    a = rng.standard_normal((m, n))
    b = rng.standard_normal((m, nrhs))
    _, x = gels_ooc(a, b, panel_cols=48)
    ref, *_ = np.linalg.lstsq(a, b, rcond=None)
    assert np.abs(x - ref).max() < 1e-8
    # wide input is rejected (the R sweep indexes n factor rows)
    with pytest.raises(Exception, match="tall"):
        gels_ooc(rng.standard_normal((96, 500)),
                 rng.standard_normal((96, 2)))


def test_geqrf_ooc_wide(rng):
    """m < n: trailing panels past kmax receive visits only."""
    from slate_tpu.linalg.ooc import geqrf_ooc, unmqr_ooc
    m, n = 160, 300
    a = rng.standard_normal((m, n))
    qr_p, taus = geqrf_ooc(a, panel_cols=128)
    R = np.triu(qr_p)
    QR = unmqr_ooc(qr_p, taus, R, trans=False, panel_cols=128)
    assert np.abs(QR - a).max() < 1e-10


def test_gemm_ooc_matches_numpy(rng):
    m, k, n = 333, 96, 64
    a = rng.standard_normal((m, k))
    b = rng.standard_normal((k, n))
    c = rng.standard_normal((m, n))
    got = gemm_ooc(2.0, a, b, -0.5, c, row_panel=100)
    ref = 2.0 * a @ b - 0.5 * c
    assert np.abs(got - ref).max() < 1e-10


def test_potrs_ooc_matches_numpy(rng):
    """Streamed Cholesky solve from the streamed factor: forward
    non-unit sweep + conjugate-transposed backward sweep, panels much
    smaller than n so multi-panel corrections run."""
    from slate_tpu.linalg.ooc import posv_ooc, potrf_ooc, potrs_ooc
    n, nrhs = 300, 3
    x = rng.standard_normal((n, n))
    a = x @ x.T / n + 4.0 * np.eye(n)
    b = rng.standard_normal((n, nrhs))
    L = potrf_ooc(a, panel_cols=128)
    got = potrs_ooc(L, b, panel_cols=128)
    ref = np.linalg.solve(a, b)
    assert np.abs(got - ref).max() / np.abs(ref).max() < 1e-10
    # bundled driver agrees
    L2, x2 = posv_ooc(a, b, panel_cols=128)
    assert np.abs(L2 - L).max() == 0
    assert np.abs(x2 - got).max() < 1e-12


def test_potrs_ooc_single_panel(rng):
    from slate_tpu.linalg.ooc import potrf_ooc, potrs_ooc
    n = 64
    x = rng.standard_normal((n, n))
    a = x @ x.T / n + 2.0 * np.eye(n)
    b = rng.standard_normal((n, 2))
    got = potrs_ooc(potrf_ooc(a, panel_cols=256), b, panel_cols=256)
    assert np.abs(got - np.linalg.solve(a, b)).max() < 1e-11


def test_potrf_ooc_invert_route(rng, monkeypatch):
    """Large-panel safety valve: when the below-block solve's expander
    temps would blow HBM, _panel_factor inverts the diag block and
    multiplies instead. Forced here by zeroing the cap; results must
    match the solve route to roundoff."""
    from slate_tpu.linalg import ooc
    n = 300
    x = rng.standard_normal((n, n))
    a = x @ x.T / n + 4.0 * np.eye(n)
    b = rng.standard_normal((n, 2))
    ref = ooc.potrf_ooc(a, panel_cols=128)
    ref_x = ooc.potrs_ooc(ref, b, panel_cols=128)
    # cap -1, not 0: solve_temps_bytes returns 0 for triangles
    # narrower than 128 and the gate is strict '>', so a zero cap
    # would let the ragged last panel keep the direct-solve route
    monkeypatch.setattr(ooc, "OOC_SOLVE_TEMP_CAP", -1)
    for k in (ooc._panel_factor, ooc._lu_visit, ooc._chol_back_visit):
        k.clear_cache()
    got = ooc.potrf_ooc(a, panel_cols=128)
    x = ooc.potrs_ooc(got, b, panel_cols=128)
    for k in (ooc._panel_factor, ooc._lu_visit, ooc._chol_back_visit):
        k.clear_cache()
    assert np.abs(got - ref).max() < 1e-10
    assert np.abs(a - got @ got.T).max() / np.abs(a).max() < 1e-12
    assert np.abs(x - ref_x).max() < 1e-9


def test_getrf_ooc_invert_route(rng, monkeypatch):
    """The LU visit's U-strip solve takes the same invert-then-matmul
    valve at OOC panel widths; forced via the zeroed cap, the whole
    factorization must still match in-core to roundoff."""
    from slate_tpu.linalg import ooc
    n = 320
    a = rng.standard_normal((n, n)) + 0.2 * n * np.eye(n)
    b = rng.standard_normal((n, 2))
    ref_lu, ref_piv = ooc.getrf_ooc(a, panel_cols=128)
    monkeypatch.setattr(ooc, "OOC_SOLVE_TEMP_CAP", -1)  # see potrf twin
    for k in (ooc._lu_visit, ooc._lu_back_visit):
        k.clear_cache()
    lu, piv = ooc.getrf_ooc(a, panel_cols=128)
    x = ooc.getrs_ooc(lu, piv, b, panel_cols=128)
    for k in (ooc._lu_visit, ooc._lu_back_visit):
        k.clear_cache()
    assert np.array_equal(piv, ref_piv)
    assert np.abs(lu - ref_lu).max() < 1e-9
    assert np.abs(a @ x - b).max() < 1e-9
