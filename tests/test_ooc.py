"""Out-of-core streaming drivers (linalg/ooc.py): the streamed panel
schedule must reproduce the in-core results exactly up to roundoff,
with HBM residency bounded by one panel (exercised here with panels
much smaller than the matrix, so every code path — multi-visit
left-looking updates, ragged last panel — runs)."""

import numpy as np
import pytest

import slate_tpu as st
from slate_tpu.linalg.ooc import gemm_ooc, potrf_ooc


@pytest.fixture
def rng():
    return np.random.default_rng(1234)


def test_potrf_ooc_matches_incore(rng):
    n = 384
    x = rng.standard_normal((n, n))
    a = x @ x.T / n + 4.0 * np.eye(n)
    L = potrf_ooc(a, panel_cols=128)
    r = a - L @ L.T
    assert np.abs(r).max() / np.abs(a).max() < 1e-12
    assert np.allclose(L, np.tril(L))


def test_potrf_ooc_ragged_panel(rng):
    n = 300                       # 300 = 2*128 + 44: ragged last panel
    x = rng.standard_normal((n, n))
    a = x @ x.T / n + 4.0 * np.eye(n)
    L = potrf_ooc(a, panel_cols=128)
    ref = np.linalg.cholesky(a)
    assert np.abs(L - ref).max() < 1e-10


def test_potrf_ooc_single_panel(rng):
    n = 64
    x = rng.standard_normal((n, n))
    a = x @ x.T / n + 2.0 * np.eye(n)
    L = potrf_ooc(a, panel_cols=256)      # whole matrix in one panel
    assert np.abs(a - L @ L.T).max() < 1e-12


def test_gemm_ooc_matches_numpy(rng):
    m, k, n = 333, 96, 64
    a = rng.standard_normal((m, k))
    b = rng.standard_normal((k, n))
    c = rng.standard_normal((m, n))
    got = gemm_ooc(2.0, a, b, -0.5, c, row_panel=100)
    ref = 2.0 * a @ b - 0.5 * c
    assert np.abs(got - ref).max() < 1e-10
