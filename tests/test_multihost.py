"""Multi-host (multi-process) smoke: the same SPMD Tiled drivers that
run on one process's mesh must run on a mesh SPANNING processes —
the reference's MPI-rank world over DCN (SURVEY §2.4: GPU-aware MPI /
multi-node grids). Simulated the way jax itself does multi-host: two
OS processes, each owning 4 virtual CPU devices, joined by
`jax.distributed.initialize` into one global 2x4 mesh; the panel
broadcasts and trailing-update reductions of the Tiled Cholesky cross
the process boundary over the Gloo CPU collectives backend.

This is the strongest multi-host evidence available without real
multi-chip hardware: the compiled program and the collective schedule
are exactly the multi-controller ones."""
import socket
import subprocess
import sys
from pathlib import Path

import pytest

WORKER = Path(__file__).with_name("multihost_worker.py")


def _run_pair(port):
    procs = [
        subprocess.Popen(
            [sys.executable, str(WORKER), str(pid), str(port)],
            stdout=subprocess.PIPE, stderr=subprocess.STDOUT, text=True)
        for pid in (0, 1)
    ]
    outs = []
    try:
        for p in procs:
            out, _ = p.communicate(timeout=420)
            outs.append(out)
    except subprocess.TimeoutExpired:
        # reap the killed children and keep their output for the
        # failure report (a bare kill leaves zombies + a silent hang);
        # drop anything collected pre-timeout so no worker's output
        # appears twice in the report
        outs = []
        for p in procs:
            p.kill()
        for p in procs:
            out, _ = p.communicate()
            outs.append(out)
        raise AssertionError(
            "multihost workers timed out\n" +
            "\n---\n".join(o[-2000:] for o in outs))
    return procs, outs


@pytest.mark.slow
def test_two_process_global_mesh_posv():
    # the free-port probe races with other processes between close and
    # the coordinator's bind; one retry with a fresh port covers the
    # overwhelmingly-rare collision without masking real failures
    for attempt in range(2):
        with socket.socket() as s:
            s.bind(("127.0.0.1", 0))
            port = s.getsockname()[1]
        procs, outs = _run_pair(port)
        if attempt == 0 and any(
                p.returncode != 0 and "Address already in use" in out
                for p, out in zip(procs, outs)):
            continue
        break
    for pid, (p, out) in enumerate(zip(procs, outs)):
        assert p.returncode == 0, (
            f"worker {pid} rc={p.returncode}\n{out[-3000:]}")
        assert f"proc {pid} resid" in out, out[-3000:]
