"""Multi-host (multi-process) smoke: the same SPMD Tiled drivers that
run on one process's mesh must run on a mesh SPANNING processes —
the reference's MPI-rank world over DCN (SURVEY §2.4: GPU-aware MPI /
multi-node grids). Simulated the way jax itself does multi-host: two
OS processes, each owning 4 virtual CPU devices, joined by
`jax.distributed.initialize` into one global 2x4 mesh; the panel
broadcasts and trailing-update reductions of the Tiled Cholesky cross
the process boundary over the Gloo CPU collectives backend.

The launch/env/handshake plumbing lives in the promoted fixture
(slate_tpu/testing/multiproc.py — ISSUE 7 satellite); this file only
asserts the posv result. The sharded-OOC multi-process coverage rides
the same fixture in test_shard_multiproc.py.

This is the strongest multi-host evidence available without real
multi-chip hardware: the compiled program and the collective schedule
are exactly the multi-controller ones."""
from pathlib import Path

import pytest

from slate_tpu.testing import multiproc as mp

WORKER = Path(__file__).with_name("multihost_worker.py")


@pytest.mark.slow
def test_two_process_global_mesh_posv():
    procs, outs = mp.launch(str(WORKER), num_processes=2)
    mp.assert_success(procs, outs)
    for pid, out in enumerate(outs):
        rec = mp.results(out).get("posv")
        assert rec is not None, out[-3000:]
        assert rec["proc"] == pid
        assert rec["resid"] < 1e-4, rec
