"""Task-graph runtime (ISSUE 17): graph construction/validation, the
deterministic executor, and the acceptance pins — ``scheduler="graph"``
BITWISE equal to the legacy walks for all three OOC streams, single
engine and sharded, at lookahead depths 0/1/2, including budget 0,
forced spills, seeded-fault determinism, and checkpoint resume from
mid-graph. The FROZEN ``ooc/scheduler`` cold route stays "walk"."""

import json

import numpy as np
import pytest

from slate_tpu.core.exceptions import SlateError
from slate_tpu.core.methods import MethodScheduler, str2method
from slate_tpu.dist import shard_ooc
from slate_tpu.linalg import ooc
from slate_tpu.obs import ledger
from slate_tpu.resil import faults, guard
from slate_tpu.sched import (FAULT_SITE_OF_KIND, NODE_KINDS,
                             PHASE_OF_KIND, TaskGraph, execute)


@pytest.fixture
def obs_on():
    from slate_tpu import obs
    from slate_tpu.obs import metrics
    obs.enable()
    obs.clear()
    metrics.reset()
    yield obs
    obs.disable()
    obs.clear()
    metrics.reset()


def _spd(rng, n, dtype=np.float64):
    x = rng.standard_normal((n, n)).astype(dtype)
    return x @ x.T / n + 4.0 * np.eye(n, dtype=dtype)


# -- graph construction + validation --------------------------------------

def test_graph_rejects_unknown_kind():
    g = TaskGraph("t")
    with pytest.raises(SlateError, match="unknown node kind"):
        g.add("frobnicate", lambda: None, key=(0,))


def test_graph_rejects_cycle():
    g = TaskGraph("t")
    a = g.add("stage", lambda: None, key=(0,))
    b = g.add("factor", lambda: None, key=(1,), deps=[a])
    g.add_edge(b, a)
    with pytest.raises(SlateError, match="cycle"):
        g.validate()


def test_graph_rejects_orphan():
    g = TaskGraph("t")
    a = g.add("stage", lambda: None, key=(0,))
    g.add("factor", lambda: None, key=(1,), deps=[a])
    g.add("writeback", lambda: None, key=(2,))     # no edges at all
    with pytest.raises(SlateError, match="orphan"):
        g.validate()


def test_graph_single_node_is_valid():
    g = TaskGraph("t")
    g.add("stage", lambda: None, key=(0,))
    g.validate()                                   # no orphan check


def test_execute_order_deps_then_priority():
    """Ready nodes pop in (key, seq) min-order; dependencies override
    priority — a low-key node waits until its dep completes."""
    order = []
    g = TaskGraph("t")
    late = g.add("factor", lambda: order.append("f9"), key=(9,))
    # key (0,) but gated on the key-(9,) node: runs LAST
    g.add("update", lambda: order.append("u0"), key=(0,),
          deps=[late])
    a = g.add("stage", lambda: order.append("s1"), key=(1,))
    g.add("writeback", lambda: order.append("w2"), key=(2,),
          deps=[a])
    execute(g, op="t")
    assert order == ["s1", "w2", "f9", "u0"]


def test_execute_slot_hooks_bracket_slots():
    begins, ends = [], []
    g = TaskGraph("t")
    a = g.add("stage", lambda: None, key=(0, 0))
    b = g.add("factor", lambda: None, key=(0, 1), deps=[a])
    g.add("writeback", lambda: None, key=(2, 0), deps=[b])
    execute(g, op="t", nt=3, begin_step=begins.append,
            end_step=ends.append)
    assert begins == [0, 2]         # empty slot 1 never opens
    assert ends == [0, 2]


def test_execute_detects_deadlock_on_key_misuse():
    """A dep whose producer never becomes ready (cycle) is a loud
    deadlock assertion, not a silent partial run."""
    g = TaskGraph("t")
    a = g.add("stage", lambda: None, key=(0,))
    b = g.add("factor", lambda: None, key=(1,), deps=[a])
    g.add_edge(b, a)
    with pytest.raises(SlateError):
        execute(g, op="t")


def test_kind_tables_total_and_on_vocabulary():
    """The SL701/SL702 contract, asserted live: every kind has a
    ledger phase and a fault-site entry, and values come from the
    registered vocabularies."""
    assert set(PHASE_OF_KIND) == set(NODE_KINDS)
    assert set(FAULT_SITE_OF_KIND) == set(NODE_KINDS)
    assert set(PHASE_OF_KIND.values()) <= set(ledger.PHASES)
    assert {s for s in FAULT_SITE_OF_KIND.values()
            if s is not None} <= set(faults.SITES)


# -- arbitration: the FROZEN cold route -----------------------------------

def test_frozen_scheduler_cold_route():
    from slate_tpu.tune.cache import FROZEN
    assert FROZEN[("ooc", "scheduler")] == "walk"
    assert MethodScheduler.resolve(4096, np.float64) \
        is MethodScheduler.Walk
    assert str2method("scheduler", "graph") is MethodScheduler.Graph
    assert str2method("scheduler", "walk") is MethodScheduler.Walk


def test_resolve_scheduler_explicit_beats_frozen():
    assert ooc._resolve_scheduler("graph", 4096, np.float64)
    assert not ooc._resolve_scheduler("walk", 4096, np.float64)
    assert not ooc._resolve_scheduler(None, 4096, np.float64)
    assert ooc._resolve_scheduler(MethodScheduler.Graph, 4096,
                                  np.float64)


# -- single-engine bitwise pins -------------------------------------------

def test_potrf_graph_bitwise(rng):
    a = _spd(rng, 160)
    for budget in (0, int(1.5 * 160 * 32 * 8)):
        L0 = ooc.potrf_ooc(a, panel_cols=32,
                           cache_budget_bytes=budget,
                           scheduler="walk")
        L1 = ooc.potrf_ooc(a, panel_cols=32,
                           cache_budget_bytes=budget,
                           scheduler="graph")
        np.testing.assert_array_equal(np.asarray(L0), np.asarray(L1))


def test_geqrf_graph_bitwise(rng):
    for shape in ((160, 160), (96, 160)):       # square + m<n tail
        g = rng.standard_normal(shape)
        qr0, tau0 = ooc.geqrf_ooc(g, panel_cols=32,
                                  cache_budget_bytes=0,
                                  scheduler="walk")
        qr1, tau1 = ooc.geqrf_ooc(g, panel_cols=32,
                                  cache_budget_bytes=0,
                                  scheduler="graph")
        assert np.array_equal(np.asarray(qr0), np.asarray(qr1))
        assert np.array_equal(np.asarray(tau0), np.asarray(tau1))


def test_getrf_tntpiv_graph_bitwise(rng):
    for shape in ((160, 160), (96, 160)):
        a = rng.standard_normal(shape) \
            * (1.0 + np.arange(shape[0]))[:, None]
        lu0, piv0 = ooc.getrf_tntpiv_ooc(a, panel_cols=32,
                                         cache_budget_bytes=0,
                                         scheduler="walk")
        lu1, piv1 = ooc.getrf_tntpiv_ooc(a, panel_cols=32,
                                         cache_budget_bytes=0,
                                         scheduler="graph")
        assert np.array_equal(np.asarray(lu0), np.asarray(lu1))
        assert np.array_equal(np.asarray(piv0), np.asarray(piv1))


# -- sharded bitwise pins (8-virtual-device mesh) -------------------------

@pytest.mark.slow
def test_shard_potrf_graph_bitwise_depths(rng, grid8):
    """The acceptance pin: sharded graph == walk at depths 0/1/2,
    budget 0 AND a forced-spill budget."""
    n, w = 160, 32
    a = _spd(rng, n)
    for depth in (0, 1, 2):
        for budget in (0, int(1.5 * n * w * 8)):
            Lw = shard_ooc.shard_potrf_ooc(
                a, grid8, panel_cols=w, lookahead=depth,
                cache_budget_bytes=budget, scheduler="walk")
            Lg = shard_ooc.shard_potrf_ooc(
                a, grid8, panel_cols=w, lookahead=depth,
                cache_budget_bytes=budget, scheduler="graph")
            assert np.array_equal(np.asarray(Lw), np.asarray(Lg)), \
                "depth %d budget %d" % (depth, budget)


@pytest.mark.slow
def test_shard_geqrf_getrf_graph_bitwise_depths(rng, grid8):
    """Same pin for QR and tournament LU, including the m<n shapes
    whose tail panels ride the graph's tail bcast nodes."""
    w = 32
    for shape in ((160, 160), (96, 160)):
        g = rng.standard_normal(shape)
        lp = g * (1.0 + np.arange(shape[0]))[:, None]
        for depth in (0, 1, 2):
            qw, tw = shard_ooc.shard_geqrf_ooc(
                g, grid8, panel_cols=w, lookahead=depth,
                scheduler="walk")
            qg, tg = shard_ooc.shard_geqrf_ooc(
                g, grid8, panel_cols=w, lookahead=depth,
                scheduler="graph")
            assert np.array_equal(np.asarray(qw), np.asarray(qg))
            assert np.array_equal(np.asarray(tw), np.asarray(tg))
            lw, pw = shard_ooc.shard_getrf_ooc(
                lp, grid8, panel_cols=w, lookahead=depth,
                scheduler="walk")
            lg, pg = shard_ooc.shard_getrf_ooc(
                lp, grid8, panel_cols=w, lookahead=depth,
                scheduler="graph")
            assert np.array_equal(np.asarray(lw), np.asarray(lg))
            assert np.array_equal(np.asarray(pw), np.asarray(pg))


@pytest.mark.slow
def test_shard_graph_staging_exact_and_ahead(rng, grid8, obs_on):
    """The graph route keeps the walk's exact staging prediction
    (depth-invariant schedule bytes) and the lookahead dispatch
    counter (nt-1 frames ahead at depth 1) — the bench --graph
    sharded leg's gates, pinned cheaply here."""
    from slate_tpu.obs import metrics
    n, w, item = 160, 32, 8
    nt = (n + w - 1) // w
    a = _spd(rng, n)
    sched = shard_ooc.CyclicSchedule(nt, grid8)
    expect = sched.staged_bytes({k: n - k * w for k in range(nt)},
                                w, n - (nt - 1) * w, item, depth=1)
    metrics.reset()
    shard_ooc.shard_potrf_ooc(a, grid8, panel_cols=w, lookahead=1,
                              cache_budget_bytes=64 * n * w * item,
                              scheduler="graph")
    c = metrics.snapshot()["counters"]
    assert int(c["ooc.h2d_bytes"]) == expect
    assert int(c["ooc.shard.bcast_ahead"]) == nt - 1
    assert int(c["sched.graphs"]) == 1
    assert int(c["sched.nodes_issued"]) > 0


def test_graph_issue_counters(rng, obs_on):
    """sched.* counters: one graph, every node issued, overhead wall
    accrued (the bench --graph per-node overhead feed)."""
    from slate_tpu.obs import metrics
    a = _spd(rng, 96)
    ooc.potrf_ooc(a, panel_cols=32, scheduler="graph")
    c = metrics.snapshot()["counters"]
    assert c.get("sched.graphs") == 1
    # nt=3: 3 stage + 3 update (0+1+2) + 3 factor + 3 writeback
    assert c.get("sched.nodes_issued") == 12
    assert c.get("sched.issue_overhead_seconds", 0) >= 0


# -- seeded-fault determinism across schedulers ---------------------------

def test_fault_log_identical_across_schedulers(rng):
    """The same seeded fault plan produces the same injection log,
    retry counts, and factor on both scheduler routes — the per-panel
    step checks and transfer guards fire in the walk's order."""
    a = _spd(rng, 160)

    def run(scheduler):
        guard.reset_counts()
        plan = faults.install(faults.FaultPlan([
            {"site": "h2d", "match": {"buf": "A"}, "times": 2,
             "prob": 0.9},
            {"site": "d2h", "match": {"buf": "L", "idx": 1},
             "times": 1},
        ], seed=11))
        L = ooc.potrf_ooc(a, panel_cols=32, scheduler=scheduler)
        faults.clear()
        return np.asarray(L), plan.log(), guard.counts()

    Lw, logw, cw = run("walk")
    Lg, logg, cg = run("graph")
    assert logw == logg
    assert cw == cg
    assert np.array_equal(Lw, Lg)


@pytest.mark.slow
def test_shard_step_faults_fire_in_same_order(rng, grid8):
    """Sharded, depth 2: the probabilistic step-site occurrence
    stream is scheduler-invariant — the graph fires the per-panel
    check exactly where the pipeline walk does, so the same seeded
    plan dies at the same step with the same log."""
    a = _spd(rng, 160)

    def run(scheduler):
        plan = faults.install(faults.FaultPlan(
            [{"site": "step", "match": {"op": "shard_potrf_ooc"},
              "times": 1, "prob": 0.4}], seed=7))
        try:
            shard_ooc.shard_potrf_ooc(a, grid8, panel_cols=32,
                                      lookahead=2,
                                      scheduler=scheduler)
            raised = None
        except faults.InjectedFault as e:
            raised = (e.site, e.ctx.get("step"), e.occurrence)
        faults.clear()
        return raised, plan.log()

    rw, logw = run("walk")
    rg, logg = run("graph")
    assert rw == rg
    assert logw == logg


# -- checkpoint/resume from mid-graph -------------------------------------

def test_potrf_graph_crash_resume_bitwise(rng, tmp_path):
    """Single-engine: crash the graph route mid-run, resume on the
    graph route, land bitwise on the uninterrupted walk factor."""
    a = _spd(rng, 160)
    L0 = np.asarray(ooc.potrf_ooc(a, panel_cols=32))
    faults.install(faults.FaultPlan(
        [{"site": "step", "match": {"op": "potrf_ooc", "step": 3},
          "times": 1}]))
    with pytest.raises(faults.InjectedFault):
        ooc.potrf_ooc(a, panel_cols=32, ckpt_path=str(tmp_path),
                      ckpt_every=1, scheduler="graph")
    faults.clear()
    meta = json.loads((tmp_path / "meta.json").read_text())
    assert meta["epoch"] == 3           # panels 0..2 durable
    L1 = np.asarray(ooc.potrf_ooc(a, panel_cols=32,
                                  ckpt_path=str(tmp_path),
                                  ckpt_every=1, scheduler="graph"))
    assert np.array_equal(L0, L1)


@pytest.mark.slow
def test_shard_graph_crash_resume_bitwise(rng, grid8, tmp_path):
    """Sharded, depth 2: resume FROM MID-GRAPH — the rebuilt graph's
    replay writebacks feed the surviving update chain, landing
    bitwise on the uninterrupted factor."""
    a = _spd(rng, 160)
    L0 = np.asarray(shard_ooc.shard_potrf_ooc(a, grid8,
                                              panel_cols=32))
    faults.install(faults.FaultPlan(
        [{"site": "step",
          "match": {"op": "shard_potrf_ooc", "step": 3},
          "times": 1}]))
    with pytest.raises(faults.InjectedFault):
        shard_ooc.shard_potrf_ooc(a, grid8, panel_cols=32,
                                  lookahead=2,
                                  ckpt_path=str(tmp_path),
                                  ckpt_every=1, scheduler="graph")
    faults.clear()
    epoch = json.loads(
        (tmp_path / "host0" / "meta.json").read_text())["epoch"]
    assert 0 < epoch <= 3               # mid-run, commit trails issue
    L1 = np.asarray(shard_ooc.shard_potrf_ooc(
        a, grid8, panel_cols=32, lookahead=2,
        ckpt_path=str(tmp_path), ckpt_every=1, scheduler="graph"))
    assert np.array_equal(L0, L1)
    # cross-scheduler resume parity: a walk crash resumed by the
    # graph route lands on the same factor too
    g = rng.standard_normal((160, 160))
    qr0, tau0 = shard_ooc.shard_geqrf_ooc(g, grid8, panel_cols=32)
    faults.install(faults.FaultPlan(
        [{"site": "step",
          "match": {"op": "shard_geqrf_ooc", "step": 2},
          "times": 1}]))
    ck2 = tmp_path / "qr"
    with pytest.raises(faults.InjectedFault):
        shard_ooc.shard_geqrf_ooc(g, grid8, panel_cols=32,
                                  ckpt_path=str(ck2), ckpt_every=1,
                                  scheduler="walk")
    faults.clear()
    qr1, tau1 = shard_ooc.shard_geqrf_ooc(
        g, grid8, panel_cols=32, lookahead=1, ckpt_path=str(ck2),
        ckpt_every=1, scheduler="graph")
    assert np.array_equal(np.asarray(qr0), np.asarray(qr1))
    assert np.array_equal(np.asarray(tau0), np.asarray(tau1))
