"""BLAS-3 driver tests (reference test/test_gemm.cc etc. residual-check
style: verify against numpy on the same data)."""

import numpy as np
import pytest

import slate_tpu as st
from slate_tpu import Diag, Norm, Side, TiledMatrix, Uplo


def M(a, nb=16):
    return TiledMatrix.from_dense(a, nb)


def test_gemm(rng):
    a = rng.standard_normal((60, 40))
    b = rng.standard_normal((40, 50))
    c = rng.standard_normal((60, 50))
    C = st.gemm(2.0, M(a), M(b), -1.0, M(c))
    np.testing.assert_allclose(C.to_numpy(), 2.0 * a @ b - c, rtol=1e-12)


def test_gemm_transposed_views(rng):
    a = rng.standard_normal((40, 60))
    b = rng.standard_normal((50, 40))
    c = rng.standard_normal((60, 50))
    C = st.gemm(1.0, M(a).T, M(b).T, 0.0, M(c))
    np.testing.assert_allclose(C.to_numpy(), a.T @ b.T, rtol=1e-12)


def test_gemm_conj_trans_complex(rng):
    a = rng.standard_normal((30, 20)) + 1j * rng.standard_normal((30, 20))
    b = rng.standard_normal((30, 25)) + 1j * rng.standard_normal((30, 25))
    c = np.zeros((20, 25), complex)
    C = st.gemm(1.0, M(a).H, M(b), 0.0, M(c))
    np.testing.assert_allclose(C.to_numpy(), a.conj().T @ b, rtol=1e-12)


def test_gemm_shape_error(rng):
    with pytest.raises(st.DimensionError):
        st.gemm(1.0, M(np.ones((4, 5))), M(np.ones((4, 5))),
                0.0, M(np.ones((4, 5))))


def test_hemm(rng):
    a = rng.standard_normal((30, 30)) + 1j * rng.standard_normal((30, 30))
    b = rng.standard_normal((30, 20)) + 1j * rng.standard_normal((30, 20))
    A = st.HermitianMatrix(Uplo.Lower, a, mb=16)
    afull = A.to_numpy()
    C = st.hemm(Side.Left, 1.5, A, M(b), 0.0, M(np.zeros((30, 20), complex)))
    np.testing.assert_allclose(C.to_numpy(), 1.5 * afull @ b, rtol=1e-12)


def test_symm_right(rng):
    a = rng.standard_normal((20, 20))
    b = rng.standard_normal((30, 20))
    A = st.SymmetricMatrix(Uplo.Upper, a, mb=16)
    afull = A.to_numpy()
    C = st.symm(Side.Right, 1.0, A, M(b), 0.0, M(np.zeros((30, 20))))
    np.testing.assert_allclose(C.to_numpy(), b @ afull, rtol=1e-12)


def test_trmm(rng):
    a = rng.standard_normal((25, 25))
    b = rng.standard_normal((25, 10))
    A = st.TriangularMatrix(Uplo.Lower, a, mb=8)
    C = st.trmm(Side.Left, 1.0, A, M(b, 8))
    np.testing.assert_allclose(C.to_numpy(), np.tril(a) @ b, rtol=1e-12)


def test_trsm_left_lower(rng):
    a = np.tril(rng.standard_normal((25, 25))) + 5 * np.eye(25)
    b = rng.standard_normal((25, 10))
    A = st.TriangularMatrix(Uplo.Lower, a, mb=8)
    X = st.trsm(Side.Left, 1.0, A, M(b, 8))
    np.testing.assert_allclose(np.tril(a) @ X.to_numpy(), b, rtol=1e-10)


def test_trsm_right_upper_unit(rng):
    a = np.triu(rng.standard_normal((20, 20)), 1) + np.eye(20)
    b = rng.standard_normal((10, 20))
    A = st.TriangularMatrix(Uplo.Upper, a, mb=8, diag=Diag.Unit)
    X = st.trsm(Side.Right, 2.0, A, M(b, 8))
    np.testing.assert_allclose(X.to_numpy() @ a, 2.0 * b, rtol=1e-10)


def test_trsm_transposed_a(rng):
    a = np.tril(rng.standard_normal((20, 20))) + 5 * np.eye(20)
    b = rng.standard_normal((20, 6))
    A = st.TriangularMatrix(Uplo.Lower, a, mb=8)
    X = st.trsm(Side.Left, 1.0, A.T, M(b, 8))
    np.testing.assert_allclose(a.T @ X.to_numpy(), b, rtol=1e-10)


def test_herk(rng):
    a = rng.standard_normal((30, 12)) + 1j * rng.standard_normal((30, 12))
    c0 = rng.standard_normal((30, 30))
    c0 = c0 + c0.T
    C = st.HermitianMatrix(Uplo.Lower, c0.astype(complex), mb=16)
    out = st.herk(2.0, M(a), 3.0, C)
    np.testing.assert_allclose(out.to_numpy(),
                               2.0 * a @ a.conj().T + 3.0 * C.to_numpy(),
                               rtol=1e-12)
    full = out.to_numpy()
    np.testing.assert_allclose(full, full.conj().T)


def test_syrk_syr2k(rng):
    a = rng.standard_normal((20, 8))
    b = rng.standard_normal((20, 8))
    C = st.SymmetricMatrix(Uplo.Lower, np.zeros((20, 20)), mb=8)
    out = st.syrk(1.0, M(a, 8), 0.0, C)
    np.testing.assert_allclose(out.to_numpy(), a @ a.T, rtol=1e-12)
    out2 = st.syr2k(1.0, M(a, 8), M(b, 8), 0.0, C)
    np.testing.assert_allclose(out2.to_numpy(), a @ b.T + b @ a.T,
                               rtol=1e-12)


def test_her2k(rng):
    a = rng.standard_normal((16, 6)) + 1j * rng.standard_normal((16, 6))
    b = rng.standard_normal((16, 6)) + 1j * rng.standard_normal((16, 6))
    C = st.HermitianMatrix(Uplo.Lower, np.zeros((16, 16), complex), mb=8)
    alpha = 1.0 + 2.0j
    out = st.her2k(alpha, M(a, 8), M(b, 8), 0.0, C)
    exp = alpha * a @ b.conj().T + np.conj(alpha) * b @ a.conj().T
    np.testing.assert_allclose(out.to_numpy(), exp, rtol=1e-12)


def test_gbmm(rng):
    a = rng.standard_normal((20, 20))
    A = st.BandMatrix(2, 3, a, mb=8)
    b = rng.standard_normal((20, 10))
    C = st.gbmm(1.0, A, M(b, 8), 0.0, M(np.zeros((20, 10)), 8))
    np.testing.assert_allclose(C.to_numpy(), A.to_numpy() @ b, rtol=1e-12)


def test_norms(rng):
    a = rng.standard_normal((30, 20))
    A = M(a)
    assert np.isclose(st.norm(Norm.Max, A), np.abs(a).max())
    assert np.isclose(st.norm(Norm.One, A), np.abs(a).sum(0).max())
    assert np.isclose(st.norm(Norm.Inf, A), np.abs(a).sum(1).max())
    assert np.isclose(st.norm(Norm.Fro, A), np.linalg.norm(a))
    np.testing.assert_allclose(st.colNorms(Norm.Max, A),
                               np.abs(a).max(0), rtol=1e-12)


def test_structured_norm(rng):
    a = rng.standard_normal((20, 20))
    S = st.SymmetricMatrix(Uplo.Lower, a, mb=8)
    full = S.to_numpy()
    assert np.isclose(st.norm(Norm.One, S), np.abs(full).sum(0).max())
    T = st.TriangularMatrix(Uplo.Upper, a, mb=8)
    assert np.isclose(st.norm(Norm.Fro, T), np.linalg.norm(np.triu(a)))


def test_add_copy_scale_set(rng):
    a = rng.standard_normal((20, 14))
    b = rng.standard_normal((20, 14))
    out = st.add(2.0, M(a, 8), 0.5, M(b, 8))
    np.testing.assert_allclose(out.to_numpy(), 2 * a + 0.5 * b, rtol=1e-12)
    cp = st.copy(M(a, 8), M(np.zeros((20, 14), np.float32), 8))
    assert cp.dtype == np.float32
    np.testing.assert_allclose(cp.to_numpy(), a.astype(np.float32))
    sc = st.scale(3.0, 2.0, M(a, 8))
    np.testing.assert_allclose(sc.to_numpy(), 1.5 * a, rtol=1e-12)
    ss = st.set(0.0, 1.0, M(a, 8))
    np.testing.assert_allclose(ss.to_numpy(), np.eye(20, 14), rtol=1e-12)
    rr = rng.standard_normal(20)
    cc = rng.standard_normal(14)
    sr = st.scale_row_col(rr, cc, M(a, 8))
    np.testing.assert_allclose(sr.to_numpy(), rr[:, None] * a * cc[None, :],
                               rtol=1e-12)


def test_set_entries(rng):
    A = M(np.zeros((10, 10)), 8)
    out = st.set_entries(lambda i, j: 1.0 * i + 0.1 * j, A)
    ii, jj = np.mgrid[0:10, 0:10]
    np.testing.assert_allclose(out.to_numpy(), ii + 0.1 * jj, rtol=1e-12)


def test_redistribute(rng):
    a = rng.standard_normal((30, 20))
    A = M(a, 16)
    B = TiledMatrix.zeros(30, 20, 8, dtype=A.dtype)
    out = st.redistribute(A, B)
    assert out.mb == 8
    np.testing.assert_allclose(out.to_numpy(), a, rtol=1e-12)


def test_gemm_jit(rng):
    import jax
    a = rng.standard_normal((32, 32))
    f = jax.jit(lambda A, B, C: st.gemm(1.0, A, B, 0.0, C))
    out = f(M(a), M(a), M(np.zeros((32, 32))))
    np.testing.assert_allclose(out.to_numpy(), a @ a, rtol=1e-12)


def test_trsm_ill_conditioned_sweep(rng):
    """Residual bound sweep over conditioning (the round-1 verdict's
    missing validation of invert-then-matmul numerics): for cond(L) up
    to ~1e6 in f64 the scaled normwise residual ||b - L x|| /
    (||L|| ||x|| n eps) must stay modest (reference
    test_gemm.cc:196-200 style error formulas)."""
    import numpy as np
    import slate_tpu as st
    n, k = 96, 4
    eps = np.finfo(np.float64).eps
    for cond in (1e2, 1e4, 1e6):
        q, _ = np.linalg.qr(rng.standard_normal((n, n)))
        a_spd = (q * np.geomspace(cond ** 2, 1.0, n)) @ q.T
        a_spd = (a_spd + a_spd.T) / 2
        L = np.linalg.cholesky(a_spd)          # cond(L) ~ cond
        b = rng.standard_normal((n, k))
        T = st.TriangularMatrix(st.Uplo.Lower, L, mb=16)
        X = st.trsm(st.Side.Left, 1.0, T,
                    st.TiledMatrix.from_dense(b, 16))
        x = X.to_numpy()
        resid = np.linalg.norm(b - L @ x) / (
            np.linalg.norm(L) * np.linalg.norm(x) * n * eps)
        assert resid < 100, f"cond={cond:g}: scaled resid {resid:.1f}"


def test_trsm_huge_rhs_slab_valve(rng, monkeypatch):
    """Above SOLVE_TEMP_CAP the single-device trsm slabs the RHS
    into independent column blocks so each direct solve's expander
    temps stay bounded (the progressive-copy temps blow HBM at
    CholQR/OOC shapes, PERF.md round-4c); forced via a negative cap
    (so the gate fires even for sub-128 triangles whose estimate is
    0), the slabbed result must match the one-shot solve."""
    import slate_tpu as st
    from slate_tpu.core.enums import Diag, MatrixType, Side, Uplo
    from slate_tpu.linalg import blocked
    n, k = 96, 24
    a = np.tril(rng.standard_normal((n, n))) + 4.0 * np.eye(n)
    b = rng.standard_normal((n, k))
    A = st.TriangularMatrix(Uplo.Lower, a, mb=32)
    B = st.Matrix(b, mb=32)
    ref = st.trsm(Side.Left, 1.0, A, B).to_numpy()
    monkeypatch.setattr(blocked, "SOLVE_TEMP_CAP", -1)
    got = st.trsm(Side.Left, 1.0, A, st.Matrix(b, mb=32)).to_numpy()
    assert np.abs(got - ref).max() / np.abs(ref).max() < 1e-11
    # right-side case (the cholqr Q = A R^-1 shape)
    ar = np.triu(rng.standard_normal((k, k))) + 4.0 * np.eye(k)
    Ar = st.TriangularMatrix(Uplo.Upper, ar, mb=8)
    Br = st.Matrix(rng.standard_normal((n, k)), mb=8)
    got_r = st.trsm(Side.Right, 1.0, Ar, Br).to_numpy()
    ref_r = Br.to_numpy() @ np.linalg.inv(ar)
    assert np.abs(got_r - ref_r).max() < 1e-10
