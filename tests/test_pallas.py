"""Pallas kernel tests. Since round 10 the kernels RUN on the CPU
test backend through the Pallas interpreter (pallas_interpret), so
tier-1 exercises the kernel bodies; the ROUTING gates
(pallas_available / *_eligible) still require real TPU, so driver
cold paths are unchanged here — test_pallas_rec.py pins that."""

import numpy as np

from slate_tpu.ops import pallas_kernels as pk


def test_gating_on_cpu():
    import jax.numpy as jnp
    # routing gates stay TPU-only on the CPU backend...
    assert not pk.pallas_available(jnp.float32)
    assert not pk.pallas_available(jnp.complex64)
    assert not pk.lu_panel_eligible(256, 64, jnp.float32)
    assert not pk.qr_panel_eligible(256, 64, jnp.float32)
    # ...while the entry points are RUNNABLE through the interpreter
    assert pk.pallas_interpret()
    assert pk.pallas_runnable(jnp.float32)
    assert pk.pallas_runnable(jnp.bfloat16)
    assert not pk.pallas_runnable(jnp.complex64)


def test_interpret_env_off(monkeypatch):
    import jax.numpy as jnp
    monkeypatch.setenv("SLATE_TPU_PALLAS_INTERPRET", "0")
    assert not pk.pallas_interpret()
    assert not pk.pallas_runnable(jnp.float32)


def test_chol_panel_fallback(rng):
    n = 64
    b = rng.standard_normal((n, n))
    spd = b @ b.T + n * np.eye(n)
    L = np.tril(np.asarray(pk.chol_panel(spd)))
    np.testing.assert_allclose(L, np.linalg.cholesky(spd), rtol=1e-9)


def test_chol_panel_ignores_upper(rng):
    # lower-only contract: stale upper-triangle content must not leak
    # into the factor (regression for the symmetrize_input hazard)
    n = 48
    b = rng.standard_normal((n, n))
    spd = b @ b.T + n * np.eye(n)
    garb = np.tril(spd) + np.triu(rng.standard_normal((n, n)), 1) * 100
    L = np.tril(np.asarray(pk.chol_panel(garb)))
    np.testing.assert_allclose(L, np.linalg.cholesky(spd), rtol=1e-9)


def test_chol_panel_interpret_f32(rng):
    # f32 at a fused-eligible shape takes the PALLAS kernel body
    # (interpreted on CPU) — the round-10 tier-1 coverage contract
    import jax.numpy as jnp
    n = 128
    b = rng.standard_normal((n, n)).astype(np.float32)
    spd = b @ b.T / n + 4.0 * np.eye(n, dtype=np.float32)
    L = np.tril(np.asarray(pk.chol_panel(jnp.asarray(spd))))
    ref = np.linalg.cholesky(spd.astype(np.float64))
    np.testing.assert_allclose(L, ref, atol=1e-3)


def test_trtri_fallback(rng):
    n = 40
    t = np.tril(rng.standard_normal((n, n))) + 4 * np.eye(n)
    inv = np.asarray(pk.trtri_lower(t))
    np.testing.assert_allclose(inv @ t, np.eye(n), atol=1e-9)
    lu = np.tril(rng.standard_normal((n, n)), -1) + np.eye(n)
    inv = np.asarray(pk.trtri_lower(lu, unit_diagonal=True))
    np.testing.assert_allclose(inv @ lu, np.eye(n), atol=1e-9)


def test_trtri_interpret_f32(rng):
    import jax.numpy as jnp
    n = 128
    t = np.tril(rng.standard_normal((n, n)).astype(np.float32)) \
        + 8.0 * np.eye(n, dtype=np.float32)
    inv = np.asarray(pk.trtri_lower(jnp.asarray(t)))
    np.testing.assert_allclose(inv @ t, np.eye(n), atol=2e-4)


def test_qr_panel_interpret_on_cpu(rng):
    # the kernel RUNS interpreted on CPU (it used to return None);
    # packed R matches numpy's up to column signs, and the reflectors
    # reconstruct A
    import jax.numpy as jnp
    m, w = 256, 64
    a = rng.standard_normal((m, w)).astype(np.float32)
    out = pk.qr_panel(jnp.asarray(a))
    assert out is not None
    packed, taus = np.asarray(out[0]), np.asarray(out[1])
    r = np.triu(packed[:w])
    r_ref = np.linalg.qr(a.astype(np.float64), mode="r")
    np.testing.assert_allclose(np.abs(r), np.abs(r_ref), atol=1e-3)
    # reconstruct: A = H_0 ... H_{w-1} R
    rec = np.zeros((m, w))
    rec[:w] = r
    for j in reversed(range(w)):
        v = np.zeros(m)
        v[j] = 1.0
        v[j + 1:] = packed[j + 1:, j]
        rec = rec - np.outer(taus[j] * v, v @ rec)
    np.testing.assert_allclose(rec, a, atol=1e-3)


def test_lu_panel_interpret_on_cpu(rng):
    # the rank-1 kernel body, interpreted: bitwise pivot parity with
    # the fori panel (same search, same update shape)
    import jax.numpy as jnp
    from slate_tpu.linalg.lu import lu_panel_fori
    m, w = 256, 32
    a = jnp.asarray(rng.standard_normal((m, w)).astype(np.float32))
    out = pk.lu_panel(a)
    assert out is not None
    packed, piv = out
    ref, piv_ref = lu_panel_fori(a)
    assert np.array_equal(np.asarray(piv), np.asarray(piv_ref))
    np.testing.assert_allclose(np.asarray(packed), np.asarray(ref),
                               atol=1e-5, rtol=1e-5)


def test_kernel_registry_shape():
    # every registry entry points at a real gate and a real entry
    for entry, (gate, tune_op) in pk.KERNEL_REGISTRY.items():
        assert callable(getattr(pk, entry))
        assert callable(getattr(pk, gate))
        assert isinstance(tune_op, str) and tune_op
