"""Pallas kernel tests. On the CPU test backend the TPU kernels are
unavailable, so these exercise the gating + fallback paths; the TPU
paths are driven on hardware by bench/verification scripts."""

import numpy as np

from slate_tpu.ops import pallas_kernels as pk


def test_gating_on_cpu():
    import jax.numpy as jnp
    assert not pk.pallas_available(jnp.float32)   # CPU backend
    assert not pk.pallas_available(jnp.complex64)


def test_chol_panel_fallback(rng):
    n = 64
    b = rng.standard_normal((n, n))
    spd = b @ b.T + n * np.eye(n)
    L = np.tril(np.asarray(pk.chol_panel(spd)))
    np.testing.assert_allclose(L, np.linalg.cholesky(spd), rtol=1e-9)


def test_chol_panel_ignores_upper(rng):
    # lower-only contract: stale upper-triangle content must not leak
    # into the factor (regression for the symmetrize_input hazard)
    n = 48
    b = rng.standard_normal((n, n))
    spd = b @ b.T + n * np.eye(n)
    garb = np.tril(spd) + np.triu(rng.standard_normal((n, n)), 1) * 100
    L = np.tril(np.asarray(pk.chol_panel(garb)))
    np.testing.assert_allclose(L, np.linalg.cholesky(spd), rtol=1e-9)


def test_trtri_fallback(rng):
    n = 40
    t = np.tril(rng.standard_normal((n, n))) + 4 * np.eye(n)
    inv = np.asarray(pk.trtri_lower(t))
    np.testing.assert_allclose(inv @ t, np.eye(n), atol=1e-9)
    lu = np.tril(rng.standard_normal((n, n)), -1) + np.eye(n)
    inv = np.asarray(pk.trtri_lower(lu, unit_diagonal=True))
    np.testing.assert_allclose(inv @ lu, np.eye(n), atol=1e-9)


def test_qr_panel_gate_off_cpu(rng):
    import jax.numpy as jnp
    assert pk.qr_panel(jnp.asarray(
        rng.standard_normal((256, 128)).astype(np.float32))) is None
