"""Pallas kernel tests. On the CPU test backend the TPU kernels are
unavailable, so these exercise the gating + fallback paths; the TPU
paths are driven on hardware by bench/verification scripts."""

import numpy as np

from slate_tpu.ops import pallas_kernels as pk


def test_gating_on_cpu():
    import jax.numpy as jnp
    assert not pk.pallas_available(jnp.float32)   # CPU backend
    assert not pk.pallas_available(jnp.complex64)


def test_syrk_lower_fallback(rng):
    n, k = 64, 16
    a = rng.standard_normal((n, k))
    c = rng.standard_normal((n, n))
    out = np.asarray(pk.syrk_lower_update(c, a))
    np.testing.assert_allclose(out, c - a @ a.T, rtol=1e-12)


def test_chol_panel_fallback(rng):
    n = 64
    b = rng.standard_normal((n, n))
    spd = b @ b.T + n * np.eye(n)
    L = np.tril(np.asarray(pk.chol_panel(spd)))
    np.testing.assert_allclose(L, np.linalg.cholesky(spd), rtol=1e-9)
