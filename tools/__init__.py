"""Repo tooling: the slate_lint static-analysis framework
(``python -m tools.slate_lint``) and the check_instrumented.py
back-compat shim over it."""
