"""CLI entry: ``python -m tools.slate_lint`` (package doc)."""

from __future__ import annotations

import argparse
import os
import sys

from . import REGISTRY, core, generate_reference
from .obs_literals import DOC_PATH


def main(argv=None) -> int:
    p = argparse.ArgumentParser(
        prog="python -m tools.slate_lint",
        description="Contract-checking static analysis (AST-only, "
                    "no jax import). Exit 0 == no live findings.")
    p.add_argument("--only", metavar="CODE|NAME",
                   help="run one analyzer (by name, code, or code "
                        "prefix, e.g. SL2 / tune-keys)")
    p.add_argument("--baseline", metavar="PATH",
                   help="JSON baseline of tolerated findings")
    p.add_argument("--write-baseline", metavar="PATH",
                   help="write the current live findings as a "
                        "baseline and exit 0")
    p.add_argument("--repo", metavar="PATH", default=None,
                   help="tree to analyze (default: this checkout)")
    p.add_argument("--list", action="store_true",
                   help="list registered analyzers and exit")
    p.add_argument("--timings", action="store_true",
                   help="report per-analyzer wall time")
    p.add_argument("--obs-doc", metavar="PATH", nargs="?",
                   const="__default__", default=None,
                   help="write the generated obs series reference "
                        "(default %s; '-' for stdout) and exit"
                        % DOC_PATH)
    args = p.parse_args(argv)

    if args.list:
        for an in REGISTRY.values():
            print("%-16s %-22s %s" % (an.name, "/".join(an.codes),
                                      an.doc))
        return 0

    repo = os.path.abspath(args.repo or core.REPO)

    if args.obs_doc is not None:
        text = generate_reference(repo)
        if args.obs_doc == "-":
            sys.stdout.write(text)
            return 0
        out = os.path.join(repo, DOC_PATH) \
            if args.obs_doc == "__default__" else args.obs_doc
        os.makedirs(os.path.dirname(out) or ".", exist_ok=True)
        with open(out, "w") as f:
            f.write(text)
        print("slate_lint: wrote %s" % out)
        return 0

    try:
        res = core.run(repo=repo, only=args.only,
                       baseline=args.baseline)
    except ValueError as e:
        print("slate_lint: %s" % e, file=sys.stderr)
        return 2

    for f, why in res.exempted:
        print("slate_lint: exempt %s (%s)" % (f.render(), why))
    for f in res.baselined:
        print("slate_lint: baselined %s" % f.render())
    for f in res.findings:
        print("slate_lint: %s" % f.render())
    if args.timings:
        for name, dt in sorted(res.timings.items(),
                               key=lambda kv: -kv[1]):
            print("slate_lint: timing %-16s %6.1f ms"
                  % (name, dt * 1e3))

    if args.write_baseline:
        core.write_baseline(args.write_baseline, res.findings)
        print("slate_lint: wrote baseline %s (%d entries)"
              % (args.write_baseline, len(res.findings)))
        return 0

    n_an = len(core.select(args.only))
    if res.findings:
        print("slate_lint: %d violation(s) (%d analyzers, %d "
              "exempted, %d baselined)"
              % (len(res.findings), n_an, len(res.exempted),
                 len(res.baselined)))
        return 1
    print("slate_lint: ok (%d analyzers, %d exempted, %d baselined)"
          % (n_an, len(res.exempted), len(res.baselined)))
    return 0


if __name__ == "__main__":
    sys.exit(main())
