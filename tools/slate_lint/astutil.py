"""Shared AST plumbing for the slate_lint analyzers.

Everything here is stdlib-only (no jax import — the tier-1-fast
contract): cached source/AST loading, call/name extraction, literal
parsing for the registry tables the analyzers cross-check
(tune/cache.FROZEN, ops/pallas_kernels.KERNEL_REGISTRY,
resil/faults.SITES), and the publish-name pattern normalizer the obs
analyzer uses for ``"prefix.%s_suffix" % x``-style dynamic series.
"""

from __future__ import annotations

import ast
import os
from typing import Dict, List, Optional, Set, Tuple

#: path -> source text / parsed module (one process == one tree scan;
#: core.run() clears between runs so tests can point at tmp trees)
_src_cache: Dict[str, str] = {}
_tree_cache: Dict[str, Optional[ast.Module]] = {}


def clear_cache() -> None:
    _src_cache.clear()
    _tree_cache.clear()


def source(path: str) -> str:
    """File text ('' when missing/unreadable)."""
    if path not in _src_cache:
        try:
            with open(path) as f:
                _src_cache[path] = f.read()
        except OSError:
            _src_cache[path] = ""
    return _src_cache[path]


def source_lines(path: str) -> List[str]:
    return source(path).splitlines()


def parse(path: str) -> Optional[ast.Module]:
    """Parsed module, or None when missing or syntactically broken
    (a broken file is the compiler's problem, not the linter's)."""
    if path not in _tree_cache:
        text = source(path)
        if not text and not os.path.exists(path):
            _tree_cache[path] = None
        else:
            try:
                _tree_cache[path] = ast.parse(text, filename=path)
            except SyntaxError:
                _tree_cache[path] = None
    return _tree_cache[path]


def py_files(root: str) -> List[str]:
    """Every .py under `root`, sorted for deterministic output."""
    out = []
    for dirpath, dirs, files in os.walk(root):
        dirs[:] = sorted(d for d in dirs if d != "__pycache__")
        for fn in sorted(files):
            if fn.endswith(".py"):
                out.append(os.path.join(dirpath, fn))
    return out


def rel(repo: str, path: str) -> str:
    return os.path.relpath(path, repo).replace(os.sep, "/")


def call_name(node: ast.Call) -> Optional[str]:
    f = node.func
    if isinstance(f, ast.Name):
        return f.id
    if isinstance(f, ast.Attribute):
        return f.attr
    return None


def const_str(node) -> Optional[str]:
    if isinstance(node, ast.Constant) and isinstance(node.value, str):
        return node.value
    return None


def calls_in(node) -> Set[str]:
    """Every function/attribute name called anywhere inside `node`."""
    out = set()
    for sub in ast.walk(node):
        if isinstance(sub, ast.Call):
            name = call_name(sub)
            if name:
                out.add(name)
    return out


def names_in(node) -> Set[str]:
    """Every bare Name referenced inside `node`."""
    return {sub.id for sub in ast.walk(node)
            if isinstance(sub, ast.Name)}


def str_consts(tree) -> Set[str]:
    return {c.value for c in ast.walk(tree)
            if isinstance(c, ast.Constant) and isinstance(c.value, str)}


def assigned_literal(path: str, name: str):
    """literal_eval of the top-level ``name = <literal>`` assignment
    in `path` (None when the file, the assignment, or literal-ness is
    missing) — the machine-readable registry tables live this way."""
    tree = parse(path)
    if tree is None:
        return None
    for node in tree.body:
        if isinstance(node, (ast.Assign, ast.AnnAssign)):
            targets = node.targets if isinstance(node, ast.Assign) \
                else [node.target]
            if any(isinstance(t, ast.Name) and t.id == name
                   for t in targets) and node.value is not None:
                try:
                    return ast.literal_eval(node.value)
                except Exception:
                    return None
    return None


def frozen_keys(path: str) -> Set[tuple]:
    """Full (op, param) keys of the FROZEN table in tune/cache.py."""
    tab = assigned_literal(path, "FROZEN")
    return set(tab) if isinstance(tab, dict) else set()


def frozen_row_lines(path: str) -> Dict[tuple, int]:
    """(op, param) -> line number of each FROZEN row (for anchoring
    orphan-row findings at the row itself)."""
    tree = parse(path)
    if tree is None:
        return {}
    for node in tree.body:
        if isinstance(node, (ast.Assign, ast.AnnAssign)):
            targets = node.targets if isinstance(node, ast.Assign) \
                else [node.target]
            if any(isinstance(t, ast.Name) and t.id == "FROZEN"
                   for t in targets) \
                    and isinstance(node.value, ast.Dict):
                out = {}
                for k in node.value.keys:
                    try:
                        key = ast.literal_eval(k)
                    except Exception:
                        continue
                    if isinstance(key, tuple):
                        out[key] = k.lineno
                return out
    return {}


def name_pattern(node) -> Optional[Tuple[str, bool]]:
    """Normalize an obs publish-name expression to (text, is_static):
    a plain string constant is static; ``"a.%s_b" % x`` and f-strings
    become wildcard patterns ('a.*_b', False); anything else (a bare
    variable) is None — nothing checkable."""
    s = const_str(node)
    if s is not None:
        return s, True
    if isinstance(node, ast.BinOp) and isinstance(node.op, ast.Mod):
        base = const_str(node.left)
        if base is not None:
            pat = base
            for spec in ("%s", "%d", "%r", "%f", "%x"):
                pat = pat.replace(spec, "*")
            return pat, False
    if isinstance(node, ast.JoinedStr):
        parts = []
        for v in node.values:
            c = const_str(v)
            parts.append(c if c is not None else "*")
        pat = "".join(parts)
        return (pat, False) if pat.strip("*") else None
    return None


def levenshtein(a: str, b: str, cap: int = 2) -> int:
    """Edit distance, early-exited at `cap` (the near-miss check only
    cares about 'is it <= 1')."""
    if a == b:
        return 0
    if abs(len(a) - len(b)) > cap:
        return cap + 1
    prev = list(range(len(b) + 1))
    for i, ca in enumerate(a, 1):
        cur = [i]
        best = i
        for j, cb in enumerate(b, 1):
            c = min(prev[j] + 1, cur[j - 1] + 1,
                    prev[j - 1] + (ca != cb))
            cur.append(c)
            best = min(best, c)
        if best > cap:
            return cap + 1
        prev = cur
    return prev[-1]
