"""slate_lint: the contract-checking static-analysis framework
(ISSUE 13 tentpole).

The codebase's load-bearing invariants live in CROSS-FILE agreements
— a FROZEN tune row in tune/cache.py and its reader in a driver, an
obs counter literal and the bench leg that reads it back, a fault
site name in a plan and the ``check()`` call that makes it fire, a
lock in ``__init__`` and the mutations it is supposed to guard. No
single call site can see a breach; this package checks the
agreements whole-tree, AST-only (no jax import — tier-1 fast), with
per-finding codes, file:line anchors, in-source exemption comments
(``# slate-lint: exempt[SLxxx] <why>``) and a JSON baseline
mechanism (core.py).

CLI::

    python -m tools.slate_lint [--only CODE|NAME] [--baseline PATH]
                               [--write-baseline PATH] [--list]
                               [--timings] [--obs-doc [PATH|-]]

Rule-numbering history (the check_instrumented.py lineage):

* ``tools/check_instrumented.py`` accreted six rules across PRs 5-12
  and is now a thin back-compat shim over :mod:`.legacy` (identical
  problem strings, pinned by tests). The old rule numbers map to:

    check_instrumented rule 1 (PR 5, ISSUE 5: public ``*_batched``
      drivers decorated)                          -> SL101
    rule 2 (PR 5/7: REQUIRED driver-op map + public ``shard_*_ooc``
      naming rule; "unobservable" messages are SL101, map losses /
      missing files SL102)                        -> SL101/SL102
    rule 3 (PR 6, ISSUE 6: KERNEL_REGISTRY gates + FROZEN tune ops)
                                                  -> SL103
    rule 4 (PR 9, ISSUE 9: ESCALATIONS ladder observable/wired/
      tunable)                                    -> SL104
    rule 5 (PR 11, ISSUE 11: shard lookahead + bcast-wait span)
                                                  -> SL105
    rule 6 (PR 12, ISSUE 12: precision arbitration + cast counters)
                                                  -> SL106

* New analyzers (this PR, ISSUE 13):

    SL201/SL202/SL203  tune-arbitration integrity (:mod:`.tune_keys`)
    SL301              lock discipline            (:mod:`.locks`)
    SL401/SL402        obs literal integrity + docs/OBS_REFERENCE.md
                                                  (:mod:`.obs_literals`)
    SL501/SL502/SL503  fault-site coverage        (:mod:`.fault_sites`)

* PR 14 (ISSUE 14):

    SL601/SL602/SL603  flight-recorder contract: step-loop
                       heartbeats, closed ledger phase set, frozen
                       off-state rows          (:mod:`.flight`)

* PR 17 (ISSUE 17):

    SL701/SL702/SL703  task-graph runtime contract: node kinds map
                       onto ledger phases and registered fault
                       sites, FROZEN ooc/scheduler row + literal
                       reader                 (:mod:`.sched_graph`)

* PR 18 (ISSUE 18):

    SL801/SL802/SL803  request-trace context integrity: serve-tier
                       escalations/counters carry trace ids, series
                       literals ride the obs registry, FROZEN
                       reqtrace/metrics gate rows + readers
                                             (:mod:`.reqtrace_ctx`)

* PR 19 (ISSUE 19):

    SL901/SL902/SL903  elastic-mesh ownership contract: the owners
                       table is the single validated source (both
                       schedule primitives read it), remap never
                       relabels the committed prefix, FROZEN mesh/*
                       rows + literal readers (:mod:`.elastic_mesh`)

* PR 20 (ISSUE 20):

    SL1001/SL1002/SL1003  fused-visit-sweep contract: the
                       fused_update kind registered with its
                       update-phase/no-own-site contract, FROZEN
                       ooc/visit_fuse row + literal reader, _mx
                       twin discipline over the fused kernels
                                             (:mod:`.visit_fuse`)

Extending: add a module with a ``@core.register(name, codes, doc)``
function ``analyze(repo) -> [core.Finding]``, import it below, and
give it one clean + one violating fixture case in
tests/test_slate_lint.py. New analyzers on a dirty tree may land
with a ``--baseline`` file; this tree carries none.
"""

from __future__ import annotations

from .core import (Finding, REGISTRY, RunResult, register, run)  # noqa: F401

# importing the analyzer modules populates the registry (order here
# == report order; legacy first so the shim's numbering leads)
from . import legacy          # noqa: F401,E402
from . import tune_keys       # noqa: F401,E402
from . import locks           # noqa: F401,E402
from . import obs_literals    # noqa: F401,E402
from . import fault_sites     # noqa: F401,E402
from . import flight          # noqa: F401,E402
from . import sched_graph     # noqa: F401,E402
from . import reqtrace_ctx    # noqa: F401,E402
from . import elastic_mesh    # noqa: F401,E402
from . import visit_fuse      # noqa: F401,E402

from .obs_literals import generate_reference  # noqa: F401,E402
