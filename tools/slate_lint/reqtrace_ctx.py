"""Analyzer (g): request-trace context integrity (SL801/SL802/SL803,
ISSUE 18).

obs/reqtrace.py's value is the JOIN: an escalation, a cache outcome,
a flush record and a latency sample all carrying the same trace id.
That join is a cross-file agreement — each publish site compiles and
runs fine with the trace dropped, and the Perfetto/ledger view then
silently shows orphaned records. These rules keep the serving tier's
publishers honest:

  SL801  trace context reaches the serving tier's records: every
         ``record_escalation("serve_*", ...)`` call in
         ``slate_tpu/serve/`` carries a ``trace=`` keyword (the
         thread-local ``current_trace_id()`` — None with tracing off,
         which the funnel's ctx filter drops), and every literal
         ``inc("serve.*")`` counter bump in ``slate_tpu/serve/``
         lives in a function that propagates trace context (calls
         ``current_trace_id`` or passes a ``trace=`` keyword to some
         call) — a serve-tier record published from a context-blind
         function cannot be joined to the request that caused it.
  SL802  series literals ride the obs-literals machinery: the
         ``sample`` publisher is registered in
         :data:`..obs_literals.WRITERS` under the ``series`` kind
         (so ``serve.latency_s`` et al. get the SL401 near-miss
         check and a docs/OBS_REFERENCE.md section), and at least
         one static ``sample("serve.…")`` publish site exists in
         ``slate_tpu/`` — a writer entry without publishers (or
         publishers invisible to the collector) is drift either way.
  SL803  the tracing/metrics arbitration ships whole: the FROZEN
         ``("obs", "reqtrace")`` and ``("serve", "metrics")`` rows
         exist in tune/cache.py AND each has a literal two-arg key
         read in ``slate_tpu/`` (the gates' ``resolve()`` memos) —
         a row without its reader ships a default nobody consults, a
         reader without the row silently falls back (the SL703
         contract, carried to the observability gates).
"""

from __future__ import annotations

import ast
import os
from typing import Iterator, List, Optional, Tuple

from . import astutil
from .core import Finding, register
from .obs_literals import WRITERS

TUNE_CACHE_PATH = "slate_tpu/tune/cache.py"
#: the two FROZEN gate rows the tracing/metrics subsystem rides
GATE_ROWS = (("obs", "reqtrace"), ("serve", "metrics"))


def _has_trace_kwarg(call: ast.Call) -> bool:
    return any(kw.arg == "trace" for kw in call.keywords)


def _propagates_trace(fn) -> bool:
    """A function participates in trace propagation when it reads the
    thread-local trace id or hands a ``trace=`` to anything."""
    for node in ast.walk(fn):
        if not isinstance(node, ast.Call):
            continue
        if astutil.call_name(node) == "current_trace_id":
            return True
        if _has_trace_kwarg(node):
            return True
    return False


def _functions(tree) -> Iterator[ast.AST]:
    for node in ast.walk(tree):
        if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
            yield node


def _serve_counter_calls(fn) -> Iterator[Tuple[int, str]]:
    """(line, name) of literal ``inc("serve.…")`` bumps directly
    inside `fn` (nested defs are visited as their own functions)."""
    stack = list(ast.iter_child_nodes(fn))
    while stack:
        node = stack.pop()
        if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
            continue
        if isinstance(node, ast.Call) \
                and astutil.call_name(node) == "inc" and node.args:
            name = astutil.const_str(node.args[0])
            if name is not None and name.startswith("serve."):
                yield node.lineno, name
        stack.extend(ast.iter_child_nodes(node))


@register("reqtrace-ctx", ("SL801", "SL802", "SL803"),
          "serve-tier escalations and counters carry trace context; "
          "series literals ride the obs-literals registry; the "
          "FROZEN reqtrace/metrics gate rows ship with literal "
          "readers (ISSUE 18)")
def analyze(repo: str) -> List[Finding]:
    findings: List[Finding] = []

    # SL801: trace context through the serving tier's publishers
    serve_dir = os.path.join(repo, "slate_tpu", "serve")
    for path in astutil.py_files(serve_dir):
        tree = astutil.parse(path)
        if tree is None:
            continue
        rel = astutil.rel(repo, path)
        for node in ast.walk(tree):
            if not isinstance(node, ast.Call) or not node.args:
                continue
            if astutil.call_name(node) != "record_escalation":
                continue
            rung = astutil.const_str(node.args[0])
            if rung is None or not rung.startswith("serve_"):
                continue
            if not _has_trace_kwarg(node):
                findings.append(Finding(
                    "SL801", rel, node.lineno,
                    "escalation %r has no trace= keyword — the "
                    "resil funnel's record cannot be joined to the "
                    "request that caused it (pass reqtrace."
                    "current_trace_id(); None is filtered with "
                    "tracing off)" % rung))
        for fn in _functions(tree):
            if _propagates_trace(fn):
                continue
            for line, name in _serve_counter_calls(fn):
                findings.append(Finding(
                    "SL801", rel, line,
                    "serve counter %r is published from %s(), which "
                    "neither reads current_trace_id() nor passes a "
                    "trace= keyword — a context-blind serve-tier "
                    "record" % (name, fn.name)))

    # SL802: the series publisher rides the obs-literals registry
    if WRITERS.get("sample") != "series":
        findings.append(Finding(
            "SL802", "tools/slate_lint/obs_literals.py", 0,
            "WRITERS has no 'sample' -> 'series' entry — series "
            "names escape the SL401 near-miss check and the "
            "OBS_REFERENCE doc"))
    else:
        pkg = os.path.join(repo, "slate_tpu")
        found = False
        for path in astutil.py_files(pkg):
            tree = astutil.parse(path)
            if tree is None:
                continue
            for node in ast.walk(tree):
                if isinstance(node, ast.Call) and node.args \
                        and astutil.call_name(node) == "sample":
                    name = astutil.const_str(node.args[0])
                    if name is not None \
                            and name.startswith("serve."):
                        found = True
                        break
            if found:
                break
        if not found:
            findings.append(Finding(
                "SL802", "slate_tpu/obs/series.py", 0,
                "no literal sample(\"serve.…\") publish site in "
                "slate_tpu/ — the series registry entry has no "
                "collectable publisher (span closure should feed "
                "the serve.latency_s family)"))

    # SL803: gate rows + literal readers (the SL703 pattern)
    tpath = os.path.join(repo, TUNE_CACHE_PATH)
    frozen = astutil.frozen_keys(tpath)
    missing_reader = {row: True for row in GATE_ROWS}
    for row in GATE_ROWS:
        if row not in frozen:
            findings.append(Finding(
                "SL803", TUNE_CACHE_PATH, 0,
                "FROZEN row %r missing — the gate's cold route must "
                "ship in the tune table" % (row,)))
    for path in astutil.py_files(os.path.join(repo, "slate_tpu")):
        tree = astutil.parse(path)
        if tree is None:
            continue
        for node in ast.walk(tree):
            if not isinstance(node, ast.Call) \
                    or len(node.args) < 2:
                continue
            key: Tuple[Optional[str], Optional[str]] = (
                astutil.const_str(node.args[0]),
                astutil.const_str(node.args[1]))
            if key in missing_reader:
                missing_reader[key] = False
        if not any(missing_reader.values()):
            break
    for row, missing in missing_reader.items():
        if missing:
            findings.append(Finding(
                "SL803", TUNE_CACHE_PATH, 0,
                "no literal %r key read anywhere in slate_tpu/ — "
                "the FROZEN gate row has no reader, so the "
                "arbitration is dead" % (row,)))
    return findings
