"""Analyzer (d): fault-site coverage (SL501/SL502/SL503).

The resil fault plans (resil/faults.py) are matched by SITE NAME
string at runtime: a plan rule ``{"site": "h2d", ...}`` fires only
where some live code path calls ``faults.check("h2d", ...)`` (or
``_guard_transfer("h2d", ...)``, which forwards its site). Nothing
validates the names: a rule naming a site that no code checks NEVER
fires — the test that injected it silently tests nothing — and a
``check()`` call site absent from the schema is an injection point
no documented plan can target.

The machine-readable schema is the ``SITES`` dict literal in
resil/faults.py (site -> short description), which the module
docstring's table mirrors.

  SL501  a SITES entry has no live ``check(site)``/
         ``_guard_transfer(site)`` call anywhere in slate_tpu/ —
         dead schema: plans naming it never fire (this is exactly
         the drift this analyzer first caught: the phantom ``panel``
         site documented since ISSUE 9 with no injection point).
  SL502  a live site literal is not in SITES — an injection point
         shipping outside the plan schema.
  SL503  a plan-rule site literal (a ``{"site": X, ...}`` dict in
         slate_tpu/, tests/, or bench.py) names a site not in SITES.
"""

from __future__ import annotations

import ast
import os
from typing import Dict, List, Tuple

from . import astutil
from .core import Finding, register

FAULTS_PATH = "slate_tpu/resil/faults.py"

#: where plan-rule dict literals live (site consumers)
PLAN_SCAN = ("slate_tpu", "tests", "bench.py")


def _live_sites(repo: str) -> Dict[str, List[Tuple[str, int]]]:
    """site -> [(rel, line)] of every ``check("site", ...)`` and
    ``_guard_transfer("site", ...)`` call in slate_tpu/. A ``check``
    call counts when its receiver names the faults module
    (``_faults.check`` / ``_rfaults.check``) or when it is a bare
    name the module imported from resil.faults — a generic
    ``.check()`` on some other object is not an injection point."""
    out: Dict[str, List[Tuple[str, int]]] = {}
    pkg = os.path.join(repo, "slate_tpu")
    for path in astutil.py_files(pkg):
        tree = astutil.parse(path)
        if tree is None:
            continue
        rel = astutil.rel(repo, path)
        # names bound by `from ...faults import check [as alias]`
        bare_checks = set()
        for node in ast.walk(tree):
            if isinstance(node, ast.ImportFrom) and node.module \
                    and node.module.split(".")[-1] == "faults":
                for a in node.names:
                    if a.name == "check":
                        bare_checks.add(a.asname or a.name)
        for node in ast.walk(tree):
            if not (isinstance(node, ast.Call) and node.args):
                continue
            name = astutil.call_name(node)
            site = astutil.const_str(node.args[0])
            if site is None:
                continue
            if name == "_guard_transfer":
                out.setdefault(site, []).append((rel, node.lineno))
            elif name == "check" or name in bare_checks:
                f = node.func
                hit = (isinstance(f, ast.Attribute)
                       and isinstance(f.value, ast.Name)
                       and "fault" in f.value.id.lower()) \
                    or (isinstance(f, ast.Name)
                        and f.id in bare_checks)
                if hit:
                    out.setdefault(site, []).append((rel, node.lineno))
    return out


def _plan_sites(repo: str) -> List[Tuple[str, str, int]]:
    """(site, rel, line) for every ``{"site": <const>, ...}`` dict
    literal in the scanned trees — fault-plan rules in drivers,
    tests, and bench legs."""
    out: List[Tuple[str, str, int]] = []
    paths: List[str] = []
    for sub in PLAN_SCAN:
        p = os.path.join(repo, sub)
        if os.path.isfile(p):
            paths.append(p)
        elif os.path.isdir(p):
            paths.extend(astutil.py_files(p))
    for path in paths:
        tree = astutil.parse(path)
        if tree is None:
            continue
        rel = astutil.rel(repo, path)
        for node in ast.walk(tree):
            if not isinstance(node, ast.Dict):
                continue
            for k, v in zip(node.keys, node.values):
                if astutil.const_str(k) == "site":
                    site = astutil.const_str(v)
                    if site is not None:
                        out.append((site, rel, node.lineno))
    return out


@register("fault-sites", ("SL501", "SL502", "SL503"),
          "every schema site has a live check() call, every live "
          "site is in the schema, every plan rule names a real site")
def analyze(repo: str) -> List[Finding]:
    findings: List[Finding] = []
    fpath = os.path.join(repo, FAULTS_PATH)
    sites = astutil.assigned_literal(fpath, "SITES")
    if not isinstance(sites, dict) or not sites:
        return [Finding(
            "SL501", FAULTS_PATH, 0,
            "SITES schema literal missing or not a plain dict — the "
            "fault-plan site names have no machine-readable registry")]
    live = _live_sites(repo)
    for site in sorted(sites):
        if site not in live:
            findings.append(Finding(
                "SL501", FAULTS_PATH, 0,
                "schema site %r has no live faults.check()/"
                "_guard_transfer() call site in slate_tpu/ — plans "
                "naming it can never fire" % site))
    for site, occurrences in sorted(live.items()):
        if site not in sites:
            rel, line = occurrences[0]
            findings.append(Finding(
                "SL502", rel, line,
                "injection site %r is checked here but absent from "
                "the SITES schema in %s — undocumented sites are "
                "untargetable by reviewed plans" % (site, FAULTS_PATH)))
    for site, rel, line in _plan_sites(repo):
        if site not in sites:
            findings.append(Finding(
                "SL503", rel, line,
                "fault-plan rule names site %r, which is not in the "
                "SITES schema (%s) — the rule can never fire, so the "
                "test/leg silently covers nothing" % (site,
                                                      FAULTS_PATH)))
    return findings
