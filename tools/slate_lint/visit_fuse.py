"""Analyzer (j): the fused-visit-sweep contract (SL1001/SL1002/
SL1003, ISSUE 20).

The fused update route only attributes, faults, and demotes
correctly when three cross-file agreements hold — none visible from
any single call site:

  SL1001 the ``fused_update`` node kind is REGISTERED with its
         contract: present in ``sched/graph.NODE_KINDS``, mapped to
         the ``"update"`` ledger phase in ``PHASE_OF_KIND`` (a fused
         node credits the update column ONCE — any other phase
         splits the bench attribution), and mapped to ``None`` in
         ``FAULT_SITE_OF_KIND`` (the members' per-panel ``step``
         checks fire INSIDE the node closure; a site of its own
         would double-inject).
  SL1002 the arbitration ships: the FROZEN ``("ooc", "visit_fuse")``
         row exists in tune/cache.py AND at least one literal
         ``("ooc", "visit_fuse")`` key read exists in slate_tpu/
         (the MethodVisitFuse.resolve route) — a row without its
         reader keeps shipping a default nobody consults.
  SL1003 mixed-precision twin discipline for the fused kernels:
         every ``_fused_sweep_*`` / ``*_visit_fused`` def has a
         ``*_mx`` twin in the same module, the twin carries the
         demoted-accumulation discipline (a literal
         ``preferred_element_type`` kwarg or a call into an ``_mx``
         helper), and the full-precision base does NOT — a fused
         route that silently skips the bf16 twin upgrades the mode's
         accuracy class on exactly the dispatches the fusion was
         meant to keep cheap.
"""

from __future__ import annotations

import ast
import os
import re
from typing import List

from . import astutil
from .core import Finding, register

GRAPH_PATH = "slate_tpu/sched/graph.py"
TUNE_CACHE_PATH = "slate_tpu/tune/cache.py"
FUSE_ROW = ("ooc", "visit_fuse")
FUSED_KIND = "fused_update"
_FUSED_DEF = re.compile(r"(^_fused_sweep_\w+$)|(^_\w+_visit_fused$)")


def _literal_row_reads(tree):
    for node in ast.walk(tree):
        if not isinstance(node, ast.Call) or len(node.args) < 2:
            continue
        if astutil.const_str(node.args[0]) == FUSE_ROW[0] \
                and astutil.const_str(node.args[1]) == FUSE_ROW[1]:
            yield node.lineno


def _mixed_markers(fn: ast.FunctionDef):
    """(has preferred_element_type kwarg, referenced *_mx names)."""
    pref = False
    mx_refs = set()
    for node in ast.walk(fn):
        if isinstance(node, ast.keyword) \
                and node.arg == "preferred_element_type":
            pref = True
        name = None
        if isinstance(node, ast.Attribute):
            name = node.attr
        elif isinstance(node, ast.Name):
            name = node.id
        if name and name.endswith("_mx") and name != fn.name:
            mx_refs.add(name)
    return pref, mx_refs


@register("visit-fuse", ("SL1001", "SL1002", "SL1003"),
          "fused_update kind registered with update-phase/no-site "
          "contract; FROZEN ooc/visit_fuse row ships with a literal "
          "reader; fused kernels carry _mx twins (ISSUE 20)")
def analyze(repo: str) -> List[Finding]:
    findings: List[Finding] = []

    # SL1001: kind tables carry the fused contract
    gpath = os.path.join(repo, GRAPH_PATH)
    kinds = astutil.assigned_literal(gpath, "NODE_KINDS")
    if not (isinstance(kinds, tuple) and FUSED_KIND in kinds):
        findings.append(Finding(
            "SL1001", GRAPH_PATH, 0,
            "node kind %r missing from NODE_KINDS — the fused sweep "
            "cannot be issued" % FUSED_KIND))
    phase_of = astutil.assigned_literal(gpath, "PHASE_OF_KIND")
    if not (isinstance(phase_of, dict)
            and phase_of.get(FUSED_KIND) == "update"):
        findings.append(Finding(
            "SL1001", GRAPH_PATH, 0,
            "PHASE_OF_KIND[%r] must be 'update' — a fused node "
            "credits the update attribution column exactly once"
            % FUSED_KIND))
    site_of = astutil.assigned_literal(gpath, "FAULT_SITE_OF_KIND")
    if not (isinstance(site_of, dict) and FUSED_KIND in site_of
            and site_of[FUSED_KIND] is None):
        findings.append(Finding(
            "SL1001", GRAPH_PATH, 0,
            "FAULT_SITE_OF_KIND[%r] must be None — the members' "
            "per-panel step checks fire inside the node closure; a "
            "site of its own would double-inject" % FUSED_KIND))

    # SL1002: the FROZEN row plus a literal reader
    tpath = os.path.join(repo, TUNE_CACHE_PATH)
    if FUSE_ROW not in astutil.frozen_keys(tpath):
        findings.append(Finding(
            "SL1002", TUNE_CACHE_PATH, 0,
            "FROZEN row %r missing — the visit-fuse cold route must "
            "ship in the tune table" % (FUSE_ROW,)))
    reads = []
    for path in astutil.py_files(os.path.join(repo, "slate_tpu")):
        tree = astutil.parse(path)
        if tree is None:
            continue
        reads.extend(_literal_row_reads(tree))
        if reads:
            break
    if not reads:
        findings.append(Finding(
            "SL1002", TUNE_CACHE_PATH, 0,
            "no literal %r key read anywhere in slate_tpu/ — the "
            "FROZEN visit-fuse row has no reader, so the "
            "arbitration is dead" % (FUSE_ROW,)))

    # SL1003: _mx twin discipline over the fused kernel defs
    for path in astutil.py_files(os.path.join(repo, "slate_tpu")):
        tree = astutil.parse(path)
        if tree is None:
            continue
        rel = os.path.relpath(path, repo)
        defs = {n.name: n for n in ast.walk(tree)
                if isinstance(n, ast.FunctionDef)}
        for name, fn in sorted(defs.items()):
            if name.endswith("_mx") or not _FUSED_DEF.match(name):
                continue
            pref, mx_refs = _mixed_markers(fn)
            if pref or mx_refs:
                findings.append(Finding(
                    "SL1003", rel, fn.lineno,
                    "full-precision fused kernel %r carries mixed-"
                    "precision markers (%s) — the base route must "
                    "stay the exact-accumulation twin"
                    % (name, "preferred_element_type" if pref
                       else ", ".join(sorted(mx_refs)))))
            twin = defs.get(name + "_mx")
            if twin is None:
                findings.append(Finding(
                    "SL1003", rel, fn.lineno,
                    "fused kernel %r has no %s_mx twin in the same "
                    "module — the bf16 route would silently run the "
                    "full-precision dispatch" % (name, name)))
                continue
            tpref, tmx = _mixed_markers(twin)
            if not (tpref or tmx):
                findings.append(Finding(
                    "SL1003", rel, twin.lineno,
                    "%r carries no mixed-precision marker (neither "
                    "a preferred_element_type kwarg nor a call into "
                    "an _mx helper) — the twin is not actually the "
                    "demoted-accumulation route" % (name + "_mx",)))
    return findings
