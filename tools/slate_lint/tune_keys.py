"""Analyzer (a): tune-arbitration integrity (SL201/SL202/SL203).

The tune subsystem's contract is cross-file: a driver reads a knob by
``(op, param)`` string key (tune/select.resolve, tuned_int,
frozen_default, get_option_tuned) and the shipped default lives as a
FROZEN row in tune/cache.py. NOTHING ties the two ends together at
runtime — a typo'd key silently resolves to the caller's fallback (or
None), and a FROZEN row whose reader was refactored away keeps
shipping a default nobody consults. Both are protocol drift of
exactly the kind PAPERS.md's BLASX/JAXMg line dies from.

  SL201  a tune key read somewhere in slate_tpu/ has no matching
         FROZEN row — exact ``(op, param)``, the ``("*", param)``
         wildcard row, or (for a dynamic op like
         ``resolve(op, "chain")``) any row with that param.
  SL202  a FROZEN row is never read anywhere (orphan row): no reader
         names its (op, param), nor param under a dynamic op, nor
         (for "*" rows) the param under any op.
  SL203  a ``str2method``/``tuned_method`` family literal is not a
         key of core/methods.str2method's family map (an unknown
         family raises KeyError at runtime, which the resolvers
         swallow into the frozen route — i.e. the typo'd entry is
         silently dead).

``tuned_method`` keys (``method_<family>``) are written only by
probes and deliberately have no FROZEN rows (tune/cache.py doc), so
they are exempt from SL201; their *family* strings are checked.
"""

from __future__ import annotations

import ast
import os
from typing import Dict, List, Set, Tuple

from . import astutil
from .core import Finding, register

TUNE_CACHE_PATH = "slate_tpu/tune/cache.py"
OPTIONS_PATH = "slate_tpu/core/options.py"
METHODS_PATH = "slate_tpu/core/methods.py"

#: files whose generic plumbing reads keys through variables (the
#: framework itself) — scanning them would only yield dynamic reads
EXCLUDE = ("slate_tpu/tune/cache.py", "slate_tpu/tune/select.py")

#: call names whose (args[0], args[1]) are an (op, param) key read
KEY_READERS = ("resolve", "_resolve", "tuned_int", "frozen_default",
               "get_param")


def _tune_param_map(repo: str) -> Dict[str, str]:
    """Option attr name -> tune param (core/options._TUNE_PARAM),
    parsed structurally: keys are ``Option.X`` attributes, values
    string constants."""
    tree = astutil.parse(os.path.join(repo, OPTIONS_PATH))
    if tree is None:
        return {}
    for node in tree.body:
        if isinstance(node, ast.Assign) \
                and any(isinstance(t, ast.Name) and t.id == "_TUNE_PARAM"
                        for t in node.targets) \
                and isinstance(node.value, ast.Dict):
            out = {}
            for k, v in zip(node.value.keys, node.value.values):
                if isinstance(k, ast.Attribute) \
                        and astutil.const_str(v) is not None:
                    out[k.attr] = v.value
            return out
    return {}


def _method_families(repo: str) -> Set[str]:
    """Keys of the ``fam`` dict literal inside methods.str2method."""
    tree = astutil.parse(os.path.join(repo, METHODS_PATH))
    if tree is None:
        return set()
    for node in ast.walk(tree):
        if isinstance(node, ast.FunctionDef) \
                and node.name == "str2method":
            for sub in ast.walk(node):
                if not (isinstance(sub, ast.Assign)
                        and any(isinstance(t, ast.Name)
                                and t.id == "fam"
                                for t in sub.targets)):
                    continue
                val = sub.value
                # the live shape is `fam = {...}[family]` — unwrap
                # the immediate subscript to the dict literal
                if isinstance(val, ast.Subscript):
                    val = val.value
                if isinstance(val, ast.Dict):
                    return {astutil.const_str(k) for k in val.keys
                            if astutil.const_str(k) is not None}
    return set()


class _Read:
    """One static key read: op/param may be None when that position
    is a runtime value (dynamic)."""

    __slots__ = ("op", "param", "rel", "line")

    def __init__(self, op, param, rel, line):
        self.op, self.param, self.rel, self.line = op, param, rel, line


def _collect(repo: str, tune_param: Dict[str, str]):
    """(key reads, family reads) across slate_tpu/."""
    reads: List[_Read] = []
    fams: List[Tuple[str, str, int]] = []   # (family, rel, line)
    pkg = os.path.join(repo, "slate_tpu")
    for path in astutil.py_files(pkg):
        rel = astutil.rel(repo, path)
        if rel in EXCLUDE:
            continue
        tree = astutil.parse(path)
        if tree is None:
            continue
        for node in ast.walk(tree):
            if not isinstance(node, ast.Call):
                continue
            name = astutil.call_name(node)
            if name in KEY_READERS and len(node.args) >= 2:
                op = astutil.const_str(node.args[0])
                param = astutil.const_str(node.args[1])
                if op is not None or param is not None:
                    reads.append(_Read(op, param, rel, node.lineno))
            elif name == "get_option_tuned" and len(node.args) >= 3:
                # (opts, Option.X, op, ...) -> (op, _TUNE_PARAM[X])
                key = node.args[1]
                if isinstance(key, ast.Attribute):
                    param = tune_param.get(key.attr)
                    if param is not None:
                        op = astutil.const_str(node.args[2])
                        reads.append(_Read(op, param, rel, node.lineno))
            elif name == "tuned_method" and len(node.args) >= 2:
                fam = astutil.const_str(node.args[1])
                if fam is not None:
                    fams.append((fam, rel, node.lineno))
            elif name == "str2method" and node.args:
                fam = astutil.const_str(node.args[0])
                if fam is not None:
                    fams.append((fam, rel, node.lineno))
    return reads, fams


@register("tune-keys", ("SL201", "SL202", "SL203"),
          "every tune key read has a FROZEN row, every FROZEN row is "
          "read somewhere, every method-family literal exists")
def analyze(repo: str) -> List[Finding]:
    findings: List[Finding] = []
    tpath = os.path.join(repo, TUNE_CACHE_PATH)
    frozen = astutil.frozen_keys(tpath)
    row_lines = astutil.frozen_row_lines(tpath)
    reads, fams = _collect(repo, _tune_param_map(repo))

    params_frozen = {p for (_o, p) in frozen}
    ops_frozen = {o for (o, _p) in frozen}

    # SL201: reads with no matching row
    for r in reads:
        if r.op is not None and r.param is not None:
            ok = (r.op, r.param) in frozen \
                or ("*", r.param) in frozen
        elif r.param is not None:        # dynamic op
            ok = r.param in params_frozen
        else:                            # dynamic param, known op
            ok = r.op in ops_frozen or r.op == "*"
        if not ok:
            key = (r.op or "<dynamic>", r.param or "<dynamic>")
            findings.append(Finding(
                "SL201", r.rel, r.line,
                "tune key (%r, %r) is read here but has no FROZEN "
                "row in %s — typo'd key, or a knob shipping without "
                "a default" % (key[0], key[1], TUNE_CACHE_PATH)))

    # SL202: orphan FROZEN rows
    read_exact = {(r.op, r.param) for r in reads
                  if r.op is not None and r.param is not None}
    read_params_dyn = {r.param for r in reads
                       if r.op is None and r.param is not None}
    read_ops_dyn = {r.op for r in reads
                    if r.param is None and r.op is not None}
    read_params_any = {r.param for r in reads if r.param is not None}
    for (op, param) in sorted(frozen):
        if op == "*":
            matched = param in read_params_any
        else:
            matched = (op, param) in read_exact \
                or param in read_params_dyn \
                or op in read_ops_dyn
        if not matched:
            findings.append(Finding(
                "SL202", TUNE_CACHE_PATH,
                row_lines.get((op, param), 0),
                "FROZEN row (%r, %r) is never read anywhere in "
                "slate_tpu/ (orphan row — its reader was removed or "
                "never wired through the arbitration)" % (op, param)))

    # SL203: unknown method families
    families = _method_families(repo)
    for fam, rel, line in fams:
        if families and fam not in families:
            findings.append(Finding(
                "SL203", rel, line,
                "str2method family %r does not exist in "
                "core/methods.str2method (known: %s) — the typo'd "
                "route silently demotes to the frozen default"
                % (fam, ", ".join(sorted(families)))))
    return findings
