"""Analyzer (h): the elastic-mesh ownership contract (SL901/SL902/
SL903, ISSUE 19).

Re-owning panels at runtime is only safe while three cross-file
agreements hold — each invisible from any single call site:

  SL901  ``dist/elastic.ElasticSchedule`` is the SINGLE source of
         ownership truth: it overrides BOTH primitive queries
         (``owner_flat`` and ``owner_coords``) and both read the
         ``owners`` table, whose ``__init__`` validation rejects any
         entry outside the mesh. Every derived query
         (owner_device/is_mine/my_panels/update_order) dispatches
         through those two primitives, so "every panel owned exactly
         once" is exactly "one validated table read by both" — a
         schedule overriding only one primitive splits ownership
         between the table and the base class's arithmetic, and two
         hosts silently both (or neither) factor a panel.
  SL902  ``ElasticSchedule.remap`` guards the committed prefix: the
         method must compare the old and new ``owners[:boundary]``
         slices and raise on mismatch — re-ownership is restricted
         to not-yet-factored panels, because a relabel of a factored
         panel orphans its broadcast frames, durable mirrors, and
         checkpoint bookkeeping.
  SL903  the ownership arbitration ships whole: the FROZEN
         ``("mesh", "ownership")`` row exists in tune/cache.py with a
         literal key read in slate_tpu/ (the MethodOwnership.resolve
         route), and every companion ``("mesh", *)`` knob row
         (remap_every / remap_threshold / throughput_alpha) likewise
         has a literal reader — a row without its reader keeps
         shipping a default nobody consults (the SL703 failure mode
         carried into the mesh layer).
"""

from __future__ import annotations

import ast
import os
from typing import List, Optional

from . import astutil
from .core import Finding, register

ELASTIC_PATH = "slate_tpu/dist/elastic.py"
TUNE_CACHE_PATH = "slate_tpu/tune/cache.py"
OWNERSHIP_ROW = ("mesh", "ownership")
#: the companion knob rows the controller resolves (SL903 checks
#: each ships with a literal reader like the gate row itself)
MESH_ROWS = (OWNERSHIP_ROW, ("mesh", "remap_every"),
             ("mesh", "remap_threshold"), ("mesh", "throughput_alpha"))


def _class(tree, name: str) -> Optional[ast.ClassDef]:
    for node in tree.body:
        if isinstance(node, ast.ClassDef) and node.name == name:
            return node
    return None


def _method(cls: ast.ClassDef, name: str) -> Optional[ast.FunctionDef]:
    for node in cls.body:
        if isinstance(node, ast.FunctionDef) and node.name == name:
            return node
    return None


def _reads_owners(fn: ast.FunctionDef) -> bool:
    """Whether `fn` reads the ``owners`` attribute (or a local bound
    from it) — the table-as-single-source check."""
    for sub in ast.walk(fn):
        if isinstance(sub, ast.Attribute) and sub.attr == "owners":
            return True
    return False


def _boundary_slices(fn: ast.FunctionDef) -> int:
    """Count of ``...[:boundary]`` subscripts inside `fn` — the
    committed-prefix comparison needs one on each side."""
    n = 0
    for sub in ast.walk(fn):
        if isinstance(sub, ast.Subscript) \
                and isinstance(sub.slice, ast.Slice) \
                and sub.slice.lower is None \
                and isinstance(sub.slice.upper, ast.Name) \
                and sub.slice.upper.id == "boundary":
            n += 1
    return n


def _literal_row_reads(tree, row) -> List[int]:
    """Lines of calls whose first two args are the literal `row` key
    (the tune_keys.KEY_READERS family shape)."""
    out = []
    for node in ast.walk(tree):
        if not isinstance(node, ast.Call) or len(node.args) < 2:
            continue
        if astutil.const_str(node.args[0]) == row[0] \
                and astutil.const_str(node.args[1]) == row[1]:
            out.append(node.lineno)
    return out


@register("elastic-mesh", ("SL901", "SL902", "SL903"),
          "elastic ownership stays single-sourced (both schedule "
          "primitives read the validated owners table), remap never "
          "relabels the committed prefix, and the FROZEN mesh/* "
          "ownership rows ship with literal readers (ISSUE 19)")
def analyze(repo: str) -> List[Finding]:
    findings: List[Finding] = []
    epath = os.path.join(repo, ELASTIC_PATH)
    tree = astutil.parse(epath)

    cls = _class(tree, "ElasticSchedule") if tree is not None else None
    if cls is None:
        findings.append(Finding(
            "SL901", ELASTIC_PATH, 0,
            "ElasticSchedule class missing — the elastic route has "
            "no ownership source"))
    else:
        # SL901: both primitives overridden, both reading the table,
        # and the table validated at construction
        for prim in ("owner_flat", "owner_coords"):
            fn = _method(cls, prim)
            if fn is None:
                findings.append(Finding(
                    "SL901", ELASTIC_PATH, cls.lineno,
                    "ElasticSchedule does not override %s() — the "
                    "base class's arithmetic answers for it, so the "
                    "owners table is no longer the single source of "
                    "ownership (a panel can be owned twice or not at "
                    "all)" % prim))
            elif not _reads_owners(fn):
                findings.append(Finding(
                    "SL901", ELASTIC_PATH, fn.lineno,
                    "ElasticSchedule.%s() does not read the owners "
                    "table — the override answers from somewhere "
                    "else, splitting ownership truth" % prim))
        init = _method(cls, "__init__")
        if init is None or not any(
                isinstance(sub, ast.Raise)
                for sub in ast.walk(init)):
            findings.append(Finding(
                "SL901", ELASTIC_PATH,
                init.lineno if init is not None else cls.lineno,
                "ElasticSchedule.__init__ does not validate the "
                "owners table (no raise) — an out-of-mesh or "
                "wrong-length table must be rejected at construction, "
                "not discovered as a missing panel mid-stream"))

        # SL902: the committed-prefix guard in remap()
        remap = _method(cls, "remap")
        if remap is None:
            findings.append(Finding(
                "SL902", ELASTIC_PATH, cls.lineno,
                "ElasticSchedule.remap() missing — re-ownership has "
                "no guarded entry point"))
        else:
            has_raise = any(isinstance(sub, ast.Raise)
                            for sub in ast.walk(remap))
            if not has_raise or _boundary_slices(remap) < 2:
                findings.append(Finding(
                    "SL902", ELASTIC_PATH, remap.lineno,
                    "ElasticSchedule.remap() does not compare the "
                    "old and new owners[:boundary] prefixes and "
                    "raise on mismatch — re-ownership must be "
                    "restricted to not-yet-factored panels (a "
                    "relabel of a committed panel orphans its "
                    "mirrors and checkpoint bookkeeping)"))

    # SL903: the FROZEN mesh rows + their literal readers
    tpath = os.path.join(repo, TUNE_CACHE_PATH)
    frozen = astutil.frozen_keys(tpath)
    trees = []
    for path in astutil.py_files(os.path.join(repo, "slate_tpu")):
        t = astutil.parse(path)
        if t is not None:
            trees.append(t)
    for row in MESH_ROWS:
        if row not in frozen:
            findings.append(Finding(
                "SL903", TUNE_CACHE_PATH, 0,
                "FROZEN row %r missing — the elastic-mesh %s must "
                "ship in the tune table"
                % (row, "gate" if row == OWNERSHIP_ROW else "knob")))
        if not any(_literal_row_reads(t, row) for t in trees):
            findings.append(Finding(
                "SL903", TUNE_CACHE_PATH, 0,
                "no literal %r key read anywhere in slate_tpu/ — "
                "the FROZEN row has no reader, so the arbitration "
                "is dead" % (row,)))
    return findings
