"""Analyzer (e): the flight-recorder contract (SL601/SL602/SL603,
ISSUE 14).

The ledger/watchdog layer only attributes correctly when three
cross-file agreements hold, none of which any single call site can
see:

  SL601  every OOC step-loop driver publishes a heartbeat: a
         module-level function in linalg/ooc.py or dist/shard_ooc.py
         whose name ends ``_ooc``, carries @instrument_driver, and
         contains a ``for`` loop must call ``heartbeat(...)``
         somewhere in its body — a loop without one is invisible to
         the stall watchdog (obs/health.py), which is exactly the
         silent-wedge class the watchdog exists to kill.
  SL602  ledger phase-name literals are a CLOSED set: every string
         literal passed to ``frame(...)``/``credit(...)`` (and every
         key of a ``phases={...}`` dict literal in an
         ``append(..., phases=...)`` call) must be in
         obs/ledger.py's ``PHASES`` tuple — a typo'd phase is a
         silently-empty attribution column, the SL401 failure mode
         carried to the ledger.
  SL603  the off-state contract ships: FROZEN rows
         ``("obs", "ledger")`` and ``("obs", "watchdog")`` exist in
         tune/cache.py, and obs/health.py publishes the
         ``health::stall`` instant + ``health.stalls`` counter the
         report/bench legs read back.
"""

from __future__ import annotations

import ast
import os
from typing import List

from . import astutil
from .core import Finding, register

LEDGER_PATH = "slate_tpu/obs/ledger.py"
HEALTH_PATH = "slate_tpu/obs/health.py"
TUNE_CACHE_PATH = "slate_tpu/tune/cache.py"
STEP_LOOP_PATHS = ("slate_tpu/linalg/ooc.py",
                   "slate_tpu/dist/shard_ooc.py")
FROZEN_ROWS = (("obs", "ledger"), ("obs", "watchdog"))
HEALTH_LITERALS = ("health::stall", "health.stalls")


def _has_instrument(node) -> bool:
    for dec in node.decorator_list:
        if isinstance(dec, ast.Call) \
                and isinstance(dec.func, ast.Name) \
                and dec.func.id == "instrument_driver":
            return True
    return False


def _phase_literal_sites(tree):
    """(literal, line) for every phase name passed to frame()/
    credit() or listed in an append(phases={...}) dict literal."""
    for node in ast.walk(tree):
        if not isinstance(node, ast.Call):
            continue
        name = astutil.call_name(node)
        if name in ("frame", "credit") and node.args:
            s = astutil.const_str(node.args[0])
            if s is not None:
                yield s, node.lineno
        elif name == "append":
            for kw in node.keywords:
                if kw.arg == "phases" and isinstance(kw.value,
                                                    ast.Dict):
                    for k in kw.value.keys:
                        s = astutil.const_str(k)
                        if s is not None:
                            yield s, k.lineno


@register("flight-recorder", ("SL601", "SL602", "SL603"),
          "every OOC step loop heartbeats the watchdog; ledger phase "
          "literals are closed-set; FROZEN obs/ledger + obs/watchdog "
          "rows and the health literals ship (ISSUE 14)")
def analyze(repo: str) -> List[Finding]:
    findings: List[Finding] = []

    # SL602 needs the authoritative phase set first
    lpath = os.path.join(repo, LEDGER_PATH)
    phases = astutil.assigned_literal(lpath, "PHASES")
    if not isinstance(phases, tuple) or not phases:
        findings.append(Finding(
            "SL603", LEDGER_PATH, 0,
            "PHASES literal missing or not a plain tuple — the "
            "closed phase set is the attribution vocabulary"))
        phases = ()
    phase_set = set(phases)

    for rel in STEP_LOOP_PATHS:
        path = os.path.join(repo, rel)
        tree = astutil.parse(path)
        if tree is None:
            findings.append(Finding("SL601", rel, 0, "file missing"))
            continue
        # SL601: heartbeat coverage of the step-loop drivers
        for node in tree.body:
            if not isinstance(node, (ast.FunctionDef,
                                     ast.AsyncFunctionDef)):
                continue
            if not node.name.endswith("_ooc") \
                    or not _has_instrument(node):
                continue
            has_loop = any(isinstance(sub, (ast.For, ast.AsyncFor))
                           for sub in ast.walk(node))
            if not has_loop:
                continue
            if "heartbeat" not in astutil.calls_in(node):
                findings.append(Finding(
                    "SL601", rel, node.lineno,
                    "step-loop driver %r publishes no heartbeat — "
                    "a wedged step is invisible to the stall "
                    "watchdog (obs/health.py)" % node.name))
        # SL602: closed-set phase literals (ledger publishers live in
        # these files plus stream.py/queue.py — scan the whole pkg
        # below instead of per-file here)
    pkg = os.path.join(repo, "slate_tpu")
    if phase_set:
        for path in astutil.py_files(pkg):
            tree = astutil.parse(path)
            if tree is None:
                continue
            rel = astutil.rel(repo, path)
            for lit, line in _phase_literal_sites(tree):
                if lit not in phase_set:
                    findings.append(Finding(
                        "SL602", rel, line,
                        "ledger phase literal %r is not in "
                        "obs/ledger.PHASES %r — a typo'd phase is a "
                        "silently-empty attribution column"
                        % (lit, tuple(sorted(phase_set)))))

    # SL603: frozen rows + health literals
    tpath = os.path.join(repo, TUNE_CACHE_PATH)
    keys = astutil.frozen_keys(tpath)
    for row in FROZEN_ROWS:
        if row not in keys:
            findings.append(Finding(
                "SL603", TUNE_CACHE_PATH, 0,
                "FROZEN row %r missing — the recorder/watchdog "
                "off-state default must ship in the tune table"
                % (row,)))
    hpath = os.path.join(repo, HEALTH_PATH)
    htree = astutil.parse(hpath)
    if htree is None:
        findings.append(Finding("SL603", HEALTH_PATH, 0,
                                "file missing"))
    else:
        consts = astutil.str_consts(htree)
        for lit in HEALTH_LITERALS:
            if lit not in consts:
                findings.append(Finding(
                    "SL603", HEALTH_PATH, 0,
                    "watchdog literal %r is not published — the "
                    "stall report/bench legs key on it" % lit))
    return findings
