"""Analyzer (f): the task-graph runtime contract (SL701/SL702/SL703,
ISSUE 17).

The sched/ runtime only attributes and faults correctly when its
static tables agree with the obs and resil vocabularies — cross-file
agreements no single call site can see:

  SL701  ``sched/graph.PHASE_OF_KIND`` maps EVERY node kind in
         ``NODE_KINDS`` and maps only into obs/ledger.py's ``PHASES``
         tuple — an unmapped kind crashes the executor's frame()
         lookup at issue time, and an off-vocabulary phase is a
         silently-empty attribution column (the SL602 failure mode
         carried into the graph runtime).
  SL702  ``sched/graph.FAULT_SITE_OF_KIND`` covers every node kind
         and its non-None values name registered fault sites
         (resil/faults.SITES) — a kind mapped to an unknown site
         advertises an injection point that can never fire.
  SL703  the scheduler arbitration ships: the FROZEN
         ``("ooc", "scheduler")`` row exists in tune/cache.py AND at
         least one literal ``("ooc", "scheduler")`` key read exists
         in slate_tpu/ (the MethodScheduler.resolve route) — a row
         without its reader keeps shipping a default nobody
         consults, a reader without the row silently falls back.
"""

from __future__ import annotations

import ast
import os
from typing import List

from . import astutil
from .core import Finding, register

GRAPH_PATH = "slate_tpu/sched/graph.py"
LEDGER_PATH = "slate_tpu/obs/ledger.py"
FAULTS_PATH = "slate_tpu/resil/faults.py"
TUNE_CACHE_PATH = "slate_tpu/tune/cache.py"
SCHED_ROW = ("ooc", "scheduler")


def _literal_row_reads(tree):
    """Lines of calls whose first two args are the literal
    ("ooc", "scheduler") key (tune_keys.KEY_READERS family)."""
    for node in ast.walk(tree):
        if not isinstance(node, ast.Call) or len(node.args) < 2:
            continue
        if astutil.const_str(node.args[0]) == SCHED_ROW[0] \
                and astutil.const_str(node.args[1]) == SCHED_ROW[1]:
            yield node.lineno


@register("sched-graph", ("SL701", "SL702", "SL703"),
          "task-graph node kinds map completely onto ledger phases "
          "and registered fault sites; the FROZEN ooc/scheduler "
          "arbitration row ships with a literal reader (ISSUE 17)")
def analyze(repo: str) -> List[Finding]:
    findings: List[Finding] = []

    gpath = os.path.join(repo, GRAPH_PATH)
    kinds = astutil.assigned_literal(gpath, "NODE_KINDS")
    if not isinstance(kinds, tuple) or not kinds:
        findings.append(Finding(
            "SL701", GRAPH_PATH, 0,
            "NODE_KINDS literal missing or not a plain tuple — the "
            "kind vocabulary is the runtime's dispatch contract"))
        kinds = ()
    kind_set = set(kinds)

    # SL701: phase map total over kinds, values in the ledger set
    phases = astutil.assigned_literal(
        os.path.join(repo, LEDGER_PATH), "PHASES")
    phase_set = set(phases) if isinstance(phases, tuple) else set()
    phase_of = astutil.assigned_literal(gpath, "PHASE_OF_KIND")
    if not isinstance(phase_of, dict):
        findings.append(Finding(
            "SL701", GRAPH_PATH, 0,
            "PHASE_OF_KIND literal missing or not a plain dict"))
        phase_of = {}
    for k in kind_set - set(phase_of):
        findings.append(Finding(
            "SL701", GRAPH_PATH, 0,
            "node kind %r has no PHASE_OF_KIND entry — the executor's "
            "ledger frame() lookup crashes at issue time" % k))
    for k, v in phase_of.items():
        if k not in kind_set:
            findings.append(Finding(
                "SL701", GRAPH_PATH, 0,
                "PHASE_OF_KIND key %r is not a NODE_KINDS kind" % k))
        if phase_set and v not in phase_set:
            findings.append(Finding(
                "SL701", GRAPH_PATH, 0,
                "PHASE_OF_KIND[%r] = %r is not in obs/ledger.PHASES "
                "%r — a silently-empty attribution column"
                % (k, v, tuple(sorted(phase_set)))))

    # SL702: fault-site map total over kinds, values registered
    sites = astutil.assigned_literal(
        os.path.join(repo, FAULTS_PATH), "SITES")
    site_set = set(sites) if isinstance(sites, dict) else set()
    site_of = astutil.assigned_literal(gpath, "FAULT_SITE_OF_KIND")
    if not isinstance(site_of, dict):
        findings.append(Finding(
            "SL702", GRAPH_PATH, 0,
            "FAULT_SITE_OF_KIND literal missing or not a plain dict"))
        site_of = {}
    for k in kind_set - set(site_of):
        findings.append(Finding(
            "SL702", GRAPH_PATH, 0,
            "node kind %r has no FAULT_SITE_OF_KIND entry (use None "
            "for kinds with no injection point)" % k))
    for k, v in site_of.items():
        if k not in kind_set:
            findings.append(Finding(
                "SL702", GRAPH_PATH, 0,
                "FAULT_SITE_OF_KIND key %r is not a NODE_KINDS "
                "kind" % k))
        if v is not None and site_set and v not in site_set:
            findings.append(Finding(
                "SL702", GRAPH_PATH, 0,
                "FAULT_SITE_OF_KIND[%r] = %r is not a registered "
                "fault site (resil/faults.SITES %r) — an injection "
                "point that can never fire"
                % (k, v, tuple(sorted(site_set)))))

    # SL703: the arbitration row plus a literal reader
    tpath = os.path.join(repo, TUNE_CACHE_PATH)
    if SCHED_ROW not in astutil.frozen_keys(tpath):
        findings.append(Finding(
            "SL703", TUNE_CACHE_PATH, 0,
            "FROZEN row %r missing — the scheduler cold route must "
            "ship in the tune table" % (SCHED_ROW,)))
    reads = []
    for path in astutil.py_files(os.path.join(repo, "slate_tpu")):
        tree = astutil.parse(path)
        if tree is None:
            continue
        reads.extend(_literal_row_reads(tree))
        if reads:
            break
    if not reads:
        findings.append(Finding(
            "SL703", TUNE_CACHE_PATH, 0,
            "no literal %r key read anywhere in slate_tpu/ — the "
            "FROZEN scheduler row has no reader, so the arbitration "
            "is dead" % (SCHED_ROW,)))
    return findings
