"""The six ``tools/check_instrumented.py`` rules (PRs 5-12), ported
as slate_lint analyzers. The check functions keep their EXACT problem
strings and ordering — ``tools/check_instrumented.py`` is now a thin
shim over this module, and the identity is pinned by
tests/test_slate_lint.py (live tree) + tests/test_tools.py (synthetic
fixtures).

Rule -> code map (numbering history in tools/slate_lint/__init__):

  rule 1 (batch drivers decorated)       -> SL101
  rule 2 (REQUIRED map + shard naming)   -> SL101/SL102
  rule 3 (Pallas kernel registry)        -> SL103
  rule 4 (resil escalation ladder)       -> SL104
  rule 5 (shard lookahead contract)      -> SL105
  rule 6 (mixed-precision contract)      -> SL106
"""

from __future__ import annotations

import ast
import os

from .astutil import calls_in, names_in, str_consts
from .core import Finding, register

#: module path -> instrument_driver op names that must stay decorated
REQUIRED = {
    "slate_tpu/linalg/chol.py": [
        "potrf", "posv", "posv_mixed", "posv_mixed_gmres"],
    "slate_tpu/linalg/lu.py": [
        "getrf", "getrf_tntpiv", "gesv", "gesv_mixed",
        "gesv_mixed_gmres", "gesv_rbt"],
    "slate_tpu/linalg/qr.py": ["geqrf", "gels", "gels_tsqr"],
    "slate_tpu/linalg/eig.py": ["heev", "hegv", "steqr2", "stedc"],
    "slate_tpu/linalg/svd.py": ["svd"],
    "slate_tpu/batch/drivers.py": [
        "potrf_batched", "getrf_batched", "geqrf_batched",
        "posv_batched", "gesv_batched", "gels_batched",
        "heev_batched", "potrs_batched", "getrs_batched"],
    "slate_tpu/dist/shard_ooc.py": [
        "shard_potrf_ooc", "shard_geqrf_ooc", "shard_getrf_ooc"],
    "slate_tpu/linalg/ooc.py": [
        "potrf_ooc", "getrf_ooc", "getrf_tntpiv_ooc", "geqrf_ooc",
        "gesv_ooc", "gels_ooc"],
}

#: relative paths of the kernel module and the tune table (rule 3)
KERNELS_PATH = "slate_tpu/ops/pallas_kernels.py"
TUNE_CACHE_PATH = "slate_tpu/tune/cache.py"

#: rule-4 paths and the tunables the resil layer must keep FROZEN
RESIL_GUARD_PATH = "slate_tpu/resil/guard.py"
RESIL_FROZEN_ROWS = (("resil", "max_retries"),
                     ("resil", "backoff_us"),
                     ("resil", "ckpt_every"))

#: rule-5 paths and contract literals (ISSUE 11)
SHARD_OOC_PATH = "slate_tpu/dist/shard_ooc.py"
SHARD_WAIT_SPAN = "shard::bcast_wait"
SHARD_WAIT_COUNTER = "ooc.shard.bcast_wait_seconds"
SHARD_LOOKAHEAD_ROW = ("ooc", "shard_lookahead")

#: rule-6 contract (ISSUE 12): drivers that must carry + resolve the
#: precision mode, the modules holding the cast/refine observability
#: literals, and the FROZEN row
PRECISION_DRIVERS = {
    "slate_tpu/linalg/ooc.py": [
        "potrf_ooc", "potrs_ooc", "posv_ooc", "getrf_ooc",
        "getrf_tntpiv_ooc", "getrs_ooc", "gesv_ooc", "geqrf_ooc"],
    "slate_tpu/dist/shard_ooc.py": [
        "shard_potrf_ooc", "shard_geqrf_ooc", "shard_getrf_ooc"],
}
CAST_COUNTER_PATH = "slate_tpu/linalg/stream.py"
CAST_COUNTERS = ("ooc.cast_demote_bytes", "ooc.cast_promote_bytes")
REFINE_SPAN_PATH = "slate_tpu/linalg/refine.py"
REFINE_SPAN = "ooc::refine"
PRECISION_ROW = ("ooc", "precision")


def _decorated_ops(path: str) -> dict:
    """function name -> instrument_driver op string (or None when a
    function has no instrument_driver decorator)."""
    with open(path) as f:
        tree = ast.parse(f.read(), filename=path)
    out = {}
    for node in tree.body:
        if not isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
            continue
        op = None
        for dec in node.decorator_list:
            if isinstance(dec, ast.Call) and isinstance(
                    dec.func, ast.Name) \
                    and dec.func.id == "instrument_driver" \
                    and dec.args \
                    and isinstance(dec.args[0], ast.Constant):
                op = dec.args[0].value
        out[node.name] = op
    return out


def _literal_registry(tree) -> dict:
    """The KERNEL_REGISTRY dict literal: entry -> (gate, tune_op)."""
    for node in tree.body:
        if isinstance(node, ast.Assign) \
                and any(isinstance(t, ast.Name)
                        and t.id == "KERNEL_REGISTRY"
                        for t in node.targets):
            try:
                return dict(ast.literal_eval(node.value))
            except Exception:
                return {}
    return {}


def _frozen_ops(path: str) -> set:
    """Op names with at least one FROZEN row in tune/cache.py."""
    return {k[0] for k in _frozen_keys(path)}


def _frozen_keys(path: str) -> set:
    """Full (op, param) keys of the FROZEN table in tune/cache.py."""
    with open(path) as f:
        tree = ast.parse(f.read(), filename=path)
    for node in tree.body:
        if isinstance(node, (ast.Assign, ast.AnnAssign)):
            targets = node.targets if isinstance(node, ast.Assign) \
                else [node.target]
            if any(isinstance(t, ast.Name) and t.id == "FROZEN"
                   for t in targets) and node.value is not None:
                try:
                    return set(ast.literal_eval(node.value))
                except Exception:
                    return set()
    return set()


def _escalation_literals(path: str) -> set:
    """String constants passed to escalate()/record_escalation()
    calls anywhere in `path` — the rung names the module wires."""
    with open(path) as f:
        try:
            tree = ast.parse(f.read(), filename=path)
        except SyntaxError:
            return set()
    out = set()
    for node in ast.walk(tree):
        if not isinstance(node, ast.Call):
            continue
        f_ = node.func
        name = f_.id if isinstance(f_, ast.Name) else (
            f_.attr if isinstance(f_, ast.Attribute) else None)
        if name not in ("escalate", "record_escalation"):
            continue
        for arg in node.args:
            if isinstance(arg, ast.Constant) \
                    and isinstance(arg.value, str):
                out.add(arg.value)
    return out


# -- rules 1 + 2: driver instrumentation hooks ---------------------------

def check_required(repo: str, required=None) -> list:
    """Rules 1/2: the REQUIRED map stays decorated; every public
    batch ``*_batched`` and sharded-OOC ``shard_*_ooc`` driver carries
    the hook."""
    required = REQUIRED if required is None else required
    problems = []
    for rel, ops in sorted(required.items()):
        path = os.path.join(repo, rel)
        if not os.path.exists(path):
            problems.append(f"{rel}: file missing (REQUIRED map stale?)")
            continue
        found = _decorated_ops(path)
        decorated = {op for op in found.values() if op}
        for op in ops:
            if op not in decorated:
                problems.append(
                    f"{rel}: driver {op!r} lost its "
                    f"@instrument_driver hook")
        if rel.endswith("batch/drivers.py"):
            for name, op in sorted(found.items()):
                if name.endswith("_batched") \
                        and not name.startswith("_") and op is None:
                    problems.append(
                        f"{rel}: public batch driver {name!r} is not "
                        f"@instrument_driver'd — batch drivers must "
                        f"not ship unobservable")
        if rel.endswith("dist/shard_ooc.py"):
            # ISSUE 7 satellite: every public sharded-OOC driver
            # (shard_*_ooc) must carry the hook — the per-host
            # Perfetto merge keys on their spans
            for name, op in sorted(found.items()):
                if name.startswith("shard_") and name.endswith("_ooc") \
                        and op is None:
                    problems.append(
                        f"{rel}: public sharded-OOC driver {name!r} "
                        f"is not @instrument_driver'd — shard_ooc "
                        f"drivers must not ship unobservable")
    return problems


# -- rule 3: Pallas kernel arbitration registry --------------------------

def check_kernel_registry(repo: str) -> list:
    """Rule 3: the Pallas kernel arbitration contract (module doc)."""
    problems = []
    kpath = os.path.join(repo, KERNELS_PATH)
    tpath = os.path.join(repo, TUNE_CACHE_PATH)
    if not os.path.exists(kpath):
        return ["%s: file missing" % KERNELS_PATH]
    with open(kpath) as f:
        tree = ast.parse(f.read(), filename=kpath)
    registry = _literal_registry(tree)
    if not registry:
        return ["%s: KERNEL_REGISTRY literal missing or not a plain "
                "dict" % KERNELS_PATH]
    funcs = {n.name: n for n in tree.body
             if isinstance(n, (ast.FunctionDef, ast.AsyncFunctionDef))}
    frozen = _frozen_ops(tpath) if os.path.exists(tpath) else set()
    # every public function that dispatches a _*_pallas kernel is a
    # registered entry point
    for name, node in sorted(funcs.items()):
        if name.startswith("_") or name in registry:
            continue
        if any(c.startswith("_") and c.endswith("_pallas")
               for c in calls_in(node)):
            problems.append(
                "%s: public kernel entry %r dispatches a Pallas "
                "kernel but is not in KERNEL_REGISTRY — every kernel "
                "needs an eligibility gate and a tune-cache key"
                % (KERNELS_PATH, name))
    for entry, spec in sorted(registry.items()):
        if not (isinstance(spec, tuple) and len(spec) == 2):
            problems.append("%s: KERNEL_REGISTRY[%r] must be "
                            "(gate, tune_op)" % (KERNELS_PATH, entry))
            continue
        gate, tune_op = spec
        if entry not in funcs:
            problems.append("%s: registered kernel entry %r does not "
                            "exist" % (KERNELS_PATH, entry))
            continue
        if gate not in funcs:
            problems.append("%s: eligibility gate %r (for %r) does "
                            "not exist" % (KERNELS_PATH, gate, entry))
        elif gate not in names_in(funcs[entry]) \
                and gate not in calls_in(funcs[entry]):
            # the entry (or its reject-reason twin it calls) must
            # consult the gate; a shared *_reject_reason helper
            # referenced by the gate itself also satisfies the
            # contract when the entry calls that helper
            gate_refs = calls_in(funcs[gate])
            if not (gate_refs & calls_in(funcs[entry])):
                problems.append(
                    "%s: kernel entry %r never consults its "
                    "registered gate %r" % (KERNELS_PATH, entry, gate))
        if tune_op not in frozen:
            problems.append(
                "%s: kernel entry %r registers tune op %r with no "
                "FROZEN row in %s — arbitration needs a shipped "
                "default" % (KERNELS_PATH, entry, tune_op,
                             TUNE_CACHE_PATH))
    return problems


# -- rule 4: resil escalation-ladder contract ----------------------------

def check_resil_contract(repo: str) -> list:
    """Rule 4: the escalation-ladder observability contract."""
    problems = []
    gpath = os.path.join(repo, RESIL_GUARD_PATH)
    tpath = os.path.join(repo, TUNE_CACHE_PATH)
    if not os.path.exists(gpath):
        return ["%s: file missing" % RESIL_GUARD_PATH]
    with open(gpath) as f:
        tree = ast.parse(f.read(), filename=gpath)
    ladder = None
    for node in tree.body:
        if isinstance(node, ast.Assign) \
                and any(isinstance(t, ast.Name)
                        and t.id == "ESCALATIONS"
                        for t in node.targets):
            try:
                ladder = dict(ast.literal_eval(node.value))
            except Exception:
                ladder = None
    if not ladder:
        return ["%s: ESCALATIONS literal missing or not a plain dict"
                % RESIL_GUARD_PATH]
    for rung, counter in sorted(ladder.items()):
        if not (isinstance(counter, str)
                and counter.startswith("resil.")):
            problems.append(
                "%s: ESCALATIONS[%r] counter %r must be resil.-"
                "prefixed (the obs namespace the report keys on)"
                % (RESIL_GUARD_PATH, rung, counter))
    funcs = {n.name: n for n in tree.body
             if isinstance(n, (ast.FunctionDef, ast.AsyncFunctionDef))}
    rec = funcs.get("record_escalation")
    if rec is None:
        problems.append("%s: record_escalation funnel missing"
                        % RESIL_GUARD_PATH)
    else:
        calls = calls_in(rec)
        if "instant" not in calls or "inc" not in calls:
            problems.append(
                "%s: record_escalation must publish an obs instant "
                "AND increment a metrics counter (found calls: %s)"
                % (RESIL_GUARD_PATH, sorted(calls)))
    # every rung wired into a driver module (outside resil/)
    wired = set()
    pkg = os.path.join(repo, "slate_tpu")
    for dirpath, _dirs, files in os.walk(pkg):
        if os.path.basename(dirpath) == "resil":
            continue
        for fn in files:
            if fn.endswith(".py"):
                wired |= _escalation_literals(
                    os.path.join(dirpath, fn))
    for rung in sorted(ladder):
        if rung not in wired:
            problems.append(
                "%s: ladder rung %r is not wired into any driver "
                "module (no escalate/record_escalation call names it)"
                % (RESIL_GUARD_PATH, rung))
    keys = _frozen_keys(tpath) if os.path.exists(tpath) else set()
    for row in RESIL_FROZEN_ROWS:
        if row not in keys:
            problems.append(
                "%s: FROZEN row %r missing from %s — the resil "
                "knobs must ship tuned defaults"
                % (RESIL_GUARD_PATH, row, TUNE_CACHE_PATH))
    return problems


# -- rule 5: sharded-OOC lookahead contract ------------------------------

def check_shard_lookahead(repo: str) -> list:
    """Rule 5: the lookahead observability/tunability contract."""
    problems = []
    spath = os.path.join(repo, SHARD_OOC_PATH)
    tpath = os.path.join(repo, TUNE_CACHE_PATH)
    if not os.path.exists(spath):
        return ["%s: file missing" % SHARD_OOC_PATH]
    with open(spath) as f:
        tree = ast.parse(f.read(), filename=spath)
    for node in tree.body:
        if not isinstance(node, (ast.FunctionDef,
                                 ast.AsyncFunctionDef)):
            continue
        name = node.name
        if not (name.startswith("shard_") and name.endswith("_ooc")):
            continue
        args = {a.arg for a in node.args.args + node.args.kwonlyargs}
        if "lookahead" not in args:
            problems.append(
                "%s: sharded-OOC driver %r has no `lookahead` "
                "parameter — every shard driver must route the "
                "broadcast-pipeline depth" % (SHARD_OOC_PATH, name))
    consts = str_consts(tree)
    if SHARD_WAIT_SPAN not in consts:
        problems.append(
            "%s: broadcast-wait span %r is not published — the "
            "lookahead's overlap fraction must stay attributable"
            % (SHARD_OOC_PATH, SHARD_WAIT_SPAN))
    if SHARD_WAIT_COUNTER not in consts:
        problems.append(
            "%s: counter %r is not published — bench/report key the "
            "per-depth broadcast-wait wall on it"
            % (SHARD_OOC_PATH, SHARD_WAIT_COUNTER))
    keys = _frozen_keys(tpath) if os.path.exists(tpath) else set()
    if SHARD_LOOKAHEAD_ROW not in keys:
        problems.append(
            "%s: FROZEN row %r missing from %s — the synchronous "
            "depth-0 default must ship in the tune table"
            % (SHARD_OOC_PATH, SHARD_LOOKAHEAD_ROW, TUNE_CACHE_PATH))
    return problems


# -- rule 6: mixed-precision streaming contract --------------------------

def check_precision_contract(repo: str, precision_drivers=None) -> list:
    """Rule 6: the mixed-precision streaming contract (module doc)."""
    precision_drivers = PRECISION_DRIVERS if precision_drivers is None \
        else precision_drivers
    problems = []
    for rel, drivers in sorted(precision_drivers.items()):
        path = os.path.join(repo, rel)
        if not os.path.exists(path):
            problems.append("%s: file missing (PRECISION_DRIVERS "
                            "stale?)" % rel)
            continue
        with open(path) as f:
            tree = ast.parse(f.read(), filename=path)
        funcs = {n.name: n for n in tree.body
                 if isinstance(n, (ast.FunctionDef,
                                   ast.AsyncFunctionDef))}
        for name in drivers:
            node = funcs.get(name)
            if node is None:
                problems.append(
                    "%s: mixed-path driver %r does not exist "
                    "(PRECISION_DRIVERS stale?)" % (rel, name))
                continue
            args = {a.arg for a in node.args.args
                    + node.args.kwonlyargs}
            if "precision" not in args:
                problems.append(
                    "%s: driver %r has no `precision` parameter — "
                    "every mixed-path OOC driver must route the "
                    "precision mode" % (rel, name))
                continue
            refs = names_in(node) | calls_in(node)
            if "_resolve_precision" not in refs \
                    and "MethodPrecision" not in refs:
                problems.append(
                    "%s: driver %r never resolves its `precision` "
                    "parameter through the tune arbitration "
                    "(_resolve_precision / MethodPrecision)"
                    % (rel, name))
    cpath = os.path.join(repo, CAST_COUNTER_PATH)
    if os.path.exists(cpath):
        with open(cpath) as f:
            consts = str_consts(ast.parse(f.read(), filename=cpath))
        for counter in CAST_COUNTERS:
            if counter not in consts:
                problems.append(
                    "%s: cast counter %r is not published — bench "
                    "must attribute how much of the H2D saving the "
                    "casts give back" % (CAST_COUNTER_PATH, counter))
    else:
        problems.append("%s: file missing" % CAST_COUNTER_PATH)
    rpath = os.path.join(repo, REFINE_SPAN_PATH)
    if os.path.exists(rpath):
        with open(rpath) as f:
            consts = str_consts(ast.parse(f.read(), filename=rpath))
        if REFINE_SPAN not in consts:
            problems.append(
                "%s: refinement span %r is not published — the "
                "mixed solves' correction wall must stay "
                "attributable" % (REFINE_SPAN_PATH, REFINE_SPAN))
    else:
        problems.append("%s: file missing" % REFINE_SPAN_PATH)
    tpath = os.path.join(repo, TUNE_CACHE_PATH)
    keys = _frozen_keys(tpath) if os.path.exists(tpath) else set()
    if PRECISION_ROW not in keys:
        problems.append(
            "FROZEN row %r missing from %s — the f32 cold-route "
            "default must ship in the tune table"
            % (PRECISION_ROW, TUNE_CACHE_PATH))
    return problems


def check_all(repo: str, required=None, precision_drivers=None) -> list:
    """All six rules, in the historical check_instrumented.check()
    order — the shim's check() output IS this list."""
    problems = check_required(repo, required=required)
    problems.extend(check_kernel_registry(repo))
    problems.extend(check_resil_contract(repo))
    problems.extend(check_shard_lookahead(repo))
    problems.extend(check_precision_contract(
        repo, precision_drivers=precision_drivers))
    return problems


# -- analyzer registrations ----------------------------------------------

def _as_findings(problems, code_of) -> list:
    out = []
    for msg in problems:
        head = msg.split(":", 1)[0]
        path = head if head.endswith(".py") else ""
        out.append(Finding(code_of(msg), path, 0, msg))
    return out


@register("instrumented", ("SL101", "SL102"),
          "every public batch/shard driver and every REQUIRED-map "
          "driver keeps its @instrument_driver hook (legacy rules "
          "1+2, ISSUEs 5/7)")
def _a_instrumented(repo):
    return _as_findings(
        check_required(repo),
        lambda m: "SL101" if "unobservable" in m else "SL102")


@register("kernel-registry", ("SL103",),
          "every Pallas kernel entry is registered with an "
          "eligibility gate and a FROZEN tune row (legacy rule 3, "
          "ISSUE 6)")
def _a_kernel_registry(repo):
    return _as_findings(check_kernel_registry(repo), lambda m: "SL103")


@register("resil-contract", ("SL104",),
          "the escalation ladder stays observable, wired, and "
          "tunable (legacy rule 4, ISSUE 9)")
def _a_resil(repo):
    return _as_findings(check_resil_contract(repo), lambda m: "SL104")


@register("shard-lookahead", ("SL105",),
          "sharded-OOC drivers route lookahead and publish the "
          "broadcast-wait span/counter (legacy rule 5, ISSUE 11)")
def _a_shard(repo):
    return _as_findings(check_shard_lookahead(repo), lambda m: "SL105")


@register("precision", ("SL106",),
          "mixed-precision drivers resolve `precision` through tune "
          "arbitration; cast counters + refine span published "
          "(legacy rule 6, ISSUE 12)")
def _a_precision(repo):
    return _as_findings(check_precision_contract(repo),
                        lambda m: "SL106")
