"""Analyzer (b): lock discipline (SL301).

The threaded layers (linalg/stream.py PanelCache/StreamEngine,
batch/queue.py, obs/metrics.py and the obs bus, resil/faults.py,
tune/cache.py) share state between the main thread, prefetch/writer
workers, and the background flusher. The convention is that state a
``with <lock>:`` block protects is ONLY mutated under that lock —
mixed discipline (some mutations locked, some not) is the race class
that survives every test until a TPU run reorders threads.

  SL301  in a lock-owning scope (a class whose ``__init__`` creates a
         ``threading.Lock``/``RLock``/``Condition`` attribute, or a
         module with one at top level), an attribute/global is
         mutated BOTH inside ``with <lock>:`` blocks and outside
         them. Each unlocked mutation site is one finding.

Deliberate lock-free paths (dispatch-free fast paths, helpers whose
callers all hold the lock) are annotated in-source::

    # slate-lint: exempt[SL301] callers hold self._lock

Scope rules (documented so exemptions stay rare and honest):

* ``__init__`` bodies and module top level are construction —
  pre-sharing, never counted.
* Nested function bodies reset the lock context (they run later,
  usually on another thread), so a worker closure mutating state
  does not inherit its definition site's lock.
* A mutation is an assignment/augmented assignment to the attribute
  (or a subscript of it), or a mutating container-method call
  (append/pop/clear/update/...). Plain reads are never flagged —
  lock-free reads of monotonic counters are this codebase's
  documented fast-path idiom.
"""

from __future__ import annotations

import ast
import os
from typing import Dict, List, Optional, Set, Tuple

from . import astutil
from .core import Finding, register

LOCK_FACTORIES = {"Lock", "RLock", "Condition"}

#: container-method names that mutate their receiver
MUTATORS = {
    "append", "appendleft", "extend", "insert", "add", "discard",
    "remove", "pop", "popleft", "popitem", "clear", "update",
    "setdefault", "move_to_end", "sort", "reverse",
}


def _is_lock_make(value) -> bool:
    """True for ``threading.Lock()`` / ``Lock()`` / RLock/Condition."""
    return isinstance(value, ast.Call) \
        and astutil.call_name(value) in LOCK_FACTORIES


def _lockish(expr) -> bool:
    """True when a with-item context expression is a lock: a Name or
    terminal Attribute whose name contains 'lock' (covers self._lock,
    module _lock, AND another object's lock like self.cache._lock —
    holding *a* lock for the mutation is the discipline; WHICH lock
    guards which attr is a design-review question, not a lint)."""
    if isinstance(expr, ast.Name):
        return "lock" in expr.id.lower()
    if isinstance(expr, ast.Attribute):
        return "lock" in expr.attr.lower()
    return False


def _target_path(expr, root: str) -> Optional[Tuple[str, ...]]:
    """Attribute path of a mutation target rooted at Name `root`
    (``self.cache.uploaded_bytes`` -> ('cache', 'uploaded_bytes')),
    unwrapping subscripts (``self._seen[i]`` mutates self._seen).
    None when not rooted there."""
    while isinstance(expr, ast.Subscript):
        expr = expr.value
    parts: List[str] = []
    while isinstance(expr, ast.Attribute):
        parts.append(expr.attr)
        expr = expr.value
    if isinstance(expr, ast.Name) and expr.id == root and parts:
        return tuple(reversed(parts))
    return None


def _global_name(expr, declared: Set[str], module_globals: Set[str],
                 call: bool = False) -> Optional[str]:
    """Name of a module-global mutation target: a plain Name REBIND
    needs a ``global`` declaration to even reach the module scope,
    but a subscript mutation (``_counters[k] = v``) or a mutating
    method call (``_counters.clear()``) hits the module object with
    no declaration."""
    sub = call
    while isinstance(expr, ast.Subscript):
        expr = expr.value
        sub = True
    if isinstance(expr, ast.Name):
        if sub and expr.id in module_globals:
            return expr.id
        if not sub and expr.id in declared:
            return expr.id
    return None


class _Site:
    __slots__ = ("line", "locked", "func")

    def __init__(self, line, locked, func):
        self.line, self.locked, self.func = line, locked, func


def _scan_func(func, is_method: bool, declared: Set[str],
               module_globals: Set[str],
               out: Dict[Tuple[str, ...], List[_Site]]) -> None:
    """Collect mutation sites in one function body, tracking whether
    each is lexically inside a lock-holding ``with``."""

    def record(path, node, locked):
        out.setdefault(path, []).append(
            _Site(node.lineno, locked, func.name))

    def targets_of(node):
        if isinstance(node, ast.Assign):
            return node.targets
        if isinstance(node, (ast.AugAssign, ast.AnnAssign)):
            return [node.target] if getattr(node, "value", True) \
                else []
        return []

    def visit(node, locked):
        if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef,
                             ast.Lambda)) and node is not func:
            # a nested def runs later (often on a worker thread): its
            # body does not inherit the definition site's lock
            for child in ast.iter_child_nodes(node):
                visit(child, False)
            return
        if isinstance(node, (ast.With, ast.AsyncWith)):
            inner = locked or any(_lockish(i.context_expr)
                                  for i in node.items)
            for i in node.items:
                visit(i.context_expr, locked)
            for child in node.body:
                visit(child, inner)
            return
        for t in targets_of(node):
            if is_method:
                path = _target_path(t, "self")
            else:
                name = _global_name(t, declared, module_globals)
                path = (name,) if name else None
            if path:
                record(path, node, locked)
        if isinstance(node, ast.Call) \
                and isinstance(node.func, ast.Attribute) \
                and node.func.attr in MUTATORS:
            if is_method:
                path = _target_path(node.func.value, "self")
            else:
                name = _global_name(node.func.value, declared,
                                    module_globals, call=True)
                path = (name,) if name else None
            if path:
                record(path, node, locked)
        for child in ast.iter_child_nodes(node):
            visit(child, locked)

    for stmt in func.body:
        visit(stmt, False)


def _class_findings(rel: str, cls: ast.ClassDef) -> List[Finding]:
    methods = [n for n in cls.body
               if isinstance(n, (ast.FunctionDef, ast.AsyncFunctionDef))]
    lock_attrs = set()
    for m in methods:
        for node in ast.walk(m):
            if isinstance(node, ast.Assign) and _is_lock_make(node.value):
                for t in node.targets:
                    p = _target_path(t, "self")
                    if p and len(p) == 1:
                        lock_attrs.add(p[0])
    if not lock_attrs:
        return []
    sites: Dict[Tuple[str, ...], List[_Site]] = {}
    for m in methods:
        if m.name == "__init__":
            continue          # construction precedes sharing
        _scan_func(m, True, set(), set(), sites)
    return _mixed(rel, " (class %s)" % cls.name, "self.", sites,
                  lock_attrs)


def _module_findings(rel: str, tree: ast.Module) -> List[Finding]:
    lock_names = set()
    module_globals = set()
    for node in tree.body:
        if isinstance(node, ast.Assign):
            for t in node.targets:
                if isinstance(t, ast.Name):
                    module_globals.add(t.id)
                    if _is_lock_make(node.value):
                        lock_names.add(t.id)
        elif isinstance(node, ast.AnnAssign) \
                and isinstance(node.target, ast.Name):
            module_globals.add(node.target.id)
    if not lock_names:
        return []
    sites: Dict[Tuple[str, ...], List[_Site]] = {}
    for node in tree.body:
        if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
            declared = {n for g in ast.walk(node)
                        if isinstance(g, ast.Global) for n in g.names}
            _scan_func(node, False, declared, module_globals, sites)
        elif isinstance(node, ast.ClassDef):
            # class methods mutating module globals (rare): scan them
            # in module mode too
            for m in node.body:
                if isinstance(m, (ast.FunctionDef,
                                  ast.AsyncFunctionDef)):
                    declared = {n for g in ast.walk(m)
                                if isinstance(g, ast.Global)
                                for n in g.names}
                    _scan_func(m, False, declared, module_globals,
                               sites)
    return _mixed(rel, " (module global)", "", sites, lock_names)


def _mixed(rel: str, scope: str, attr_prefix: str,
           sites: Dict[Tuple[str, ...], List[_Site]],
           lock_names: Set[str]) -> List[Finding]:
    findings = []
    for path, ss in sorted(sites.items()):
        if path[0] in lock_names or path[-1] in lock_names:
            continue                      # the lock itself
        locked = [s for s in ss if s.locked]
        unlocked = [s for s in ss if not s.locked]
        if not (locked and unlocked):
            continue
        attr = attr_prefix + ".".join(path)
        for s in sorted(unlocked, key=lambda s: s.line):
            findings.append(Finding(
                "SL301", rel, s.line,
                "%s%s is mutated under a lock elsewhere (e.g. %s, "
                "line %d) but without one here in %s() — mixed lock "
                "discipline; take the lock, or annotate a deliberate "
                "lock-free path with `# slate-lint: exempt[SL301] "
                "<why>`" % (attr, scope, locked[0].func,
                            locked[0].line, s.func)))
    return findings


@register("lock-discipline", ("SL301",),
          "state mutated under a lock somewhere is never mutated "
          "lock-free elsewhere in the same class/module")
def analyze(repo: str) -> List[Finding]:
    findings: List[Finding] = []
    pkg = os.path.join(repo, "slate_tpu")
    for path in astutil.py_files(pkg):
        tree = astutil.parse(path)
        if tree is None:
            continue
        rel = astutil.rel(repo, path)
        findings.extend(_module_findings(rel, tree))
        for node in tree.body:
            if isinstance(node, ast.ClassDef):
                findings.extend(_class_findings(rel, node))
    return findings
