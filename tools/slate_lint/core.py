"""Framework core: findings, the analyzer registry, in-source
exemption comments, the baseline mechanism, and the runner.

Contracts:

* A **Finding** is one violation: (code, repo-relative path, line,
  message). Codes are stable (``SLxyz``); exemptions key on the code
  alone, and a baseline entry may omit its ``message`` to match
  every finding of its (code, path) — the form that survives message
  rewording.
* An **analyzer** is a registered named pass ``fn(repo) ->
  [Finding]``; registration binds its finding codes, so ``--only``
  can select by analyzer name or code (prefix).
* An **exemption** is an in-source annotation on (or up to two lines
  above) the flagged line::

      # slate-lint: exempt[SL301] <one-line justification>

  The justification is REQUIRED — a bare marker does not exempt.
  Exempted findings are reported separately and never fail the run.
* A **baseline** is a JSON file of finding keys (code/path/message)
  to tolerate — the adoption ramp for a new analyzer on a dirty
  tree. ``--write-baseline`` emits one; a baselined finding is
  reported but does not fail the run. (This PR lands with ZERO
  baseline entries — the mechanism exists for future analyzers.)
"""

from __future__ import annotations

import dataclasses
import json
import os
import re
import time
from typing import Callable, Dict, List, Optional, Tuple

from . import astutil

#: repo root (tools/slate_lint/core.py -> repo)
REPO = os.path.dirname(os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))))


@dataclasses.dataclass(frozen=True)
class Finding:
    code: str
    path: str          # repo-relative; "" for repo-wide findings
    line: int          # 1-based; 0 when not line-anchored
    message: str

    def render(self) -> str:
        if self.path and self.line:
            return "%s %s:%d: %s" % (self.code, self.path, self.line,
                                     self.message)
        if self.path:
            return "%s %s: %s" % (self.code, self.path, self.message)
        return "%s %s" % (self.code, self.message)

    def key(self) -> Dict[str, str]:
        return {"code": self.code, "path": self.path,
                "message": self.message}


@dataclasses.dataclass(frozen=True)
class Analyzer:
    name: str
    codes: Tuple[str, ...]
    doc: str
    fn: Callable


#: name -> Analyzer, in registration order (== report order)
REGISTRY: Dict[str, Analyzer] = {}


def register(name: str, codes, doc: str):
    """Decorator: register ``fn(repo) -> [Finding]`` under `name`."""
    def deco(fn):
        REGISTRY[name] = Analyzer(name, tuple(codes), doc, fn)
        return fn
    return deco


def select(only: Optional[str]) -> List[Analyzer]:
    """Analyzers matching ``--only`` (name, exact code, or code
    prefix); all of them when `only` is falsy."""
    ans = list(REGISTRY.values())
    if not only:
        return ans
    hit = [a for a in ans
           if a.name == only or only in a.codes
           or any(c.startswith(only) for c in a.codes)]
    if not hit:
        raise ValueError(
            "--only %r matches no analyzer (have: %s)"
            % (only, ", ".join("%s %s" % (a.name, "/".join(a.codes))
                               for a in ans)))
    return hit


# -- exemption comments -------------------------------------------------

_EXEMPT_RE = re.compile(
    r"#\s*slate-lint:\s*exempt\[(SL\d+)\]\s+(\S.*?)\s*$")


def exemption(repo: str, f: Finding) -> Optional[str]:
    """The justification string when `f`'s line (or one of the two
    lines above it) carries a matching exempt annotation, else None."""
    if not f.path or not f.line:
        return None
    lines = astutil.source_lines(os.path.join(repo, f.path))
    for ln in range(f.line, max(f.line - 3, 0), -1):
        if 0 < ln <= len(lines):
            m = _EXEMPT_RE.search(lines[ln - 1])
            if m and m.group(1) == f.code:
                return m.group(2)
    return None


# -- baseline -----------------------------------------------------------

BASELINE_VERSION = 1


def load_baseline(path: Optional[str]) -> List[Dict[str, str]]:
    if not path or not os.path.exists(path):
        return []
    try:
        with open(path) as f:
            raw = json.load(f)
        if isinstance(raw, dict) \
                and raw.get("version") == BASELINE_VERSION \
                and isinstance(raw.get("entries"), list):
            return [e for e in raw["entries"] if isinstance(e, dict)]
    except Exception:
        pass
    return []


def write_baseline(path: str, findings: List[Finding]) -> str:
    with open(path, "w") as f:
        json.dump({"version": BASELINE_VERSION,
                   "entries": [fi.key() for fi in findings]},
                  f, indent=1, sort_keys=True)
        f.write("\n")
    return path


def _baselined(entries: List[Dict[str, str]], f: Finding) -> bool:
    k = f.key()
    return any(e.get("code") == k["code"] and e.get("path") == k["path"]
               and e.get("message", k["message"]) == k["message"]
               for e in entries)


# -- runner -------------------------------------------------------------

@dataclasses.dataclass
class RunResult:
    findings: List[Finding]                    # live violations
    exempted: List[Tuple[Finding, str]]        # (finding, why)
    baselined: List[Finding]
    timings: Dict[str, float]                  # analyzer -> seconds

    @property
    def ok(self) -> bool:
        return not self.findings


def run(repo: Optional[str] = None, only: Optional[str] = None,
        baseline: Optional[str] = None) -> RunResult:
    """Run the selected analyzers over `repo` and classify every
    finding as live / exempted / baselined."""
    repo = os.path.abspath(repo or REPO)
    astutil.clear_cache()
    entries = load_baseline(baseline)
    res = RunResult([], [], [], {})
    for an in select(only):
        t0 = time.perf_counter()
        found = an.fn(repo)
        res.timings[an.name] = time.perf_counter() - t0
        for f in found:
            why = exemption(repo, f)
            if why is not None:
                res.exempted.append((f, why))
            elif _baselined(entries, f):
                res.baselined.append(f)
            else:
                res.findings.append(f)
    return res
