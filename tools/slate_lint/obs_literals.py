"""Analyzer (c): obs literal integrity (SL401/SL402).

Every counter/histogram/gauge/span/instant series in the codebase is
born from a string literal at its publish site — ``inc("ooc.h2d_
bytes", ...)``, ``span("shard::bcast_wait")`` — and read back by
name in bench legs, the report, and the PERF rounds. A one-off typo
(``batch.dispatchs``) creates a silently-EMPTY series: the publisher
feeds the typo, the reader sees zeros, and a PERF round then
"measures" an improvement that is actually a dead counter. The
near-miss check makes that class of drift a lint failure instead of
a wrong conclusion.

  SL401  two distinct published names of the same kind are a
         near-miss pair: Levenshtein distance 1, or identical after
         separator normalization (``.``/``_``/``::``/``-`` treated
         equal). Different kinds (a counter vs an instant) may
         legitimately share stems (``resil.fallbacks`` /
         ``resil::fallback``) and are not compared.
  SL402  docs/OBS_REFERENCE.md does not match the generated registry
         (regenerate with ``python -m tools.slate_lint --obs-doc``).

Dynamic names (``"ooc.%s_invalidations" % cause``) are collected as
``*`` wildcard patterns: they appear in the reference doc and are
near-miss-compared against each other, but never against static
names (a pattern legitimately brackets many concrete series).
"""

from __future__ import annotations

import ast
import os
from typing import Dict, List, Tuple

from . import astutil
from .core import Finding, register

DOC_PATH = "docs/OBS_REFERENCE.md"

#: publisher call name -> series kind
WRITERS = {
    "inc": "counter",
    "flag_concrete": "counter",
    "counter": "counter",          # events.counter(): a counter track
    "observe": "histogram",
    "observe_concrete": "histogram",
    "set_gauge": "gauge",
    "span": "span",
    "instant": "instant",
    "sample": "series",            # obs/series.py time-series samples
}

KIND_ORDER = ("counter", "histogram", "gauge", "span", "instant",
              "series")
KIND_TITLES = {"counter": "Counters", "histogram": "Histograms",
               "gauge": "Gauges", "span": "Spans",
               "instant": "Instants", "series": "Series"}

_SEPS = str.maketrans("", "", "._:-")


def _normalize(name: str) -> str:
    return name.translate(_SEPS)


class Entry:
    __slots__ = ("kind", "name", "static", "sites")

    def __init__(self, kind, name, static):
        self.kind, self.name, self.static = kind, name, static
        self.sites: List[Tuple[str, int]] = []   # (rel, line)


def collect(repo: str) -> Dict[Tuple[str, str], Entry]:
    """(kind, name) -> Entry for every publish literal/pattern in
    slate_tpu/ (plus obs/metrics.py's direct ``_counters[...]``
    literal writes — jit.traces/jit.recompiles are published that
    way, under the registry lock)."""
    out: Dict[Tuple[str, str], Entry] = {}

    def add(kind, name, static, rel, line):
        e = out.get((kind, name))
        if e is None:
            e = out[(kind, name)] = Entry(kind, name, static)
        e.sites.append((rel, line))

    pkg = os.path.join(repo, "slate_tpu")
    for path in astutil.py_files(pkg):
        tree = astutil.parse(path)
        if tree is None:
            continue
        rel = astutil.rel(repo, path)
        is_metrics = rel.endswith("obs/metrics.py")
        for node in ast.walk(tree):
            if isinstance(node, ast.Call) and node.args:
                kind = WRITERS.get(astutil.call_name(node))
                if kind is not None:
                    pat = astutil.name_pattern(node.args[0])
                    if pat is not None:
                        add(kind, pat[0], pat[1], rel, node.lineno)
            elif is_metrics and isinstance(node, ast.Assign):
                for t in node.targets:
                    if isinstance(t, ast.Subscript) \
                            and isinstance(t.value, ast.Name) \
                            and t.value.id == "_counters":
                        pat = astutil.name_pattern(t.slice)
                        if pat is not None:
                            add("counter", pat[0], pat[1], rel,
                                node.lineno)
    return out


def generate_reference(repo: str) -> str:
    """The markdown registry docs/OBS_REFERENCE.md must equal."""
    entries = collect(repo)
    lines = [
        "# Observability series reference",
        "",
        "Every counter / histogram / gauge / span / instant name",
        "published in `slate_tpu/`, with the modules that publish it.",
        "Names containing `*` are dynamic patterns (the publisher",
        "formats a runtime value into the series name).",
        "",
        "GENERATED FILE — regenerate with",
        "`python -m tools.slate_lint --obs-doc` after adding or",
        "renaming a series; lint rule SL402",
        "(tools/slate_lint/obs_literals.py) fails when this file",
        "drifts from the publish sites.",
    ]
    for kind in KIND_ORDER:
        es = [e for (k, _n), e in sorted(entries.items())
              if k == kind]
        if not es:
            continue
        lines += ["", "## %s" % KIND_TITLES[kind], "",
                  "| series | published by |", "|---|---|"]
        for e in es:
            mods = sorted({rel for rel, _l in e.sites})
            lines.append("| `%s` | %s |"
                         % (e.name,
                            ", ".join("`%s`" % m for m in mods)))
    return "\n".join(lines) + "\n"


@register("obs-literals", ("SL401", "SL402"),
          "no near-miss series names (typo'd literals make silently-"
          "empty series); docs/OBS_REFERENCE.md matches the "
          "generated registry")
def analyze(repo: str) -> List[Finding]:
    findings: List[Finding] = []
    entries = collect(repo)
    by_kind: Dict[str, List[Entry]] = {}
    for (kind, _name), e in sorted(entries.items()):
        by_kind.setdefault(kind, []).append(e)
    for kind, es in sorted(by_kind.items()):
        for i, a in enumerate(es):
            for b in es[i + 1:]:
                if a.static != b.static:
                    continue     # a pattern brackets many names
                near = astutil.levenshtein(a.name, b.name, cap=1) == 1 \
                    or (_normalize(a.name) == _normalize(b.name))
                if near:
                    rel, line = b.sites[0]
                    findings.append(Finding(
                        "SL401", rel, line,
                        "obs %s literal %r is a near-miss of %r "
                        "(published at %s:%d) — a one-off typo makes "
                        "a silently-empty series; unify the names"
                        % (kind, b.name, a.name, a.sites[0][0],
                           a.sites[0][1])))
    doc = os.path.join(repo, DOC_PATH)
    want = generate_reference(repo)
    have = astutil.source(doc)
    if not have:
        findings.append(Finding(
            "SL402", DOC_PATH, 0,
            "missing — generate it with `python -m tools.slate_lint "
            "--obs-doc`"))
    elif have != want:
        findings.append(Finding(
            "SL402", DOC_PATH, 0,
            "stale — the checked-in registry no longer matches the "
            "publish sites; regenerate with `python -m "
            "tools.slate_lint --obs-doc`"))
    return findings
