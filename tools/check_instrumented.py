#!/usr/bin/env python
"""Instrumentation lint (ISSUE 5 satellite): every public batch driver
and every driver on the instrumented-contract list must carry
``@instrument_driver`` — new drivers must not ship unobservable, and a
refactor must not silently drop a hook the obs report keys on.

Two rules, both static (AST — no jax import, fast enough for tier-1):

  1. slate_tpu/batch/drivers.py: EVERY public module-level function
     whose name ends in ``_batched`` is decorated. The batch layer is
     the serving tier; an unobservable batched driver would make
     occupancy/dispatch accounting silently lie.
  2. The REQUIRED map below (module -> driver ops) stays decorated.
     The list is the obs contract as of ISSUE 5 — extend it when
     instrumenting a new driver, never trim it to silence the lint.

Exit 0 clean; exit 1 with one line per violation (CI wires this into
tier-1 via tests/test_tools.py).
"""

from __future__ import annotations

import ast
import os
import sys

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))

#: module path -> instrument_driver op names that must stay decorated
REQUIRED = {
    "slate_tpu/linalg/chol.py": [
        "potrf", "posv", "posv_mixed", "posv_mixed_gmres"],
    "slate_tpu/linalg/lu.py": [
        "getrf", "getrf_tntpiv", "gesv", "gesv_mixed",
        "gesv_mixed_gmres", "gesv_rbt"],
    "slate_tpu/linalg/qr.py": ["geqrf", "gels", "gels_tsqr"],
    "slate_tpu/linalg/eig.py": ["heev", "hegv", "steqr2", "stedc"],
    "slate_tpu/linalg/svd.py": ["svd"],
    "slate_tpu/batch/drivers.py": [
        "potrf_batched", "getrf_batched", "geqrf_batched",
        "posv_batched", "gesv_batched", "gels_batched",
        "heev_batched"],
}


def _decorated_ops(path: str) -> dict:
    """function name -> instrument_driver op string (or None when a
    function has no instrument_driver decorator)."""
    with open(path) as f:
        tree = ast.parse(f.read(), filename=path)
    out = {}
    for node in tree.body:
        if not isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
            continue
        op = None
        for dec in node.decorator_list:
            if isinstance(dec, ast.Call) and isinstance(
                    dec.func, ast.Name) \
                    and dec.func.id == "instrument_driver" \
                    and dec.args \
                    and isinstance(dec.args[0], ast.Constant):
                op = dec.args[0].value
        out[node.name] = op
    return out


def check(repo: str = REPO) -> list:
    problems = []
    for rel, ops in sorted(REQUIRED.items()):
        path = os.path.join(repo, rel)
        if not os.path.exists(path):
            problems.append(f"{rel}: file missing (REQUIRED map stale?)")
            continue
        found = _decorated_ops(path)
        decorated = {op for op in found.values() if op}
        for op in ops:
            if op not in decorated:
                problems.append(
                    f"{rel}: driver {op!r} lost its "
                    f"@instrument_driver hook")
        if rel.endswith("batch/drivers.py"):
            for name, op in sorted(found.items()):
                if name.endswith("_batched") \
                        and not name.startswith("_") and op is None:
                    problems.append(
                        f"{rel}: public batch driver {name!r} is not "
                        f"@instrument_driver'd — batch drivers must "
                        f"not ship unobservable")
    return problems


def main() -> int:
    problems = check()
    for p in problems:
        print("check_instrumented: %s" % p)
    if problems:
        return 1
    print("check_instrumented: ok (%d modules)" % len(REQUIRED))
    return 0


if __name__ == "__main__":
    sys.exit(main())
