#!/usr/bin/env python
"""Back-compat shim over ``tools/slate_lint`` (ISSUE 13).

This script accreted six contract rules across PRs 5-12 as a 537-line
monolith; those rules now live as slate_lint analyzers SL101-SL106 in
``tools/slate_lint/legacy.py`` (the rule->code map is in
``tools/slate_lint/__init__``), alongside the SL2xx-SL5xx analyzers
nothing checked before. The shim keeps the historical surface —
``check()``, the per-rule ``check_*`` functions, the configuration
maps (monkeypatched by tests), the problem strings, and the CLI exit
codes — IDENTICAL, so existing wiring keeps passing while new callers
use::

    python -m tools.slate_lint

Run directly it prints a one-line deprecation pointer on stderr.
"""

from __future__ import annotations

import os
import sys

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
if REPO not in sys.path:
    sys.path.insert(0, REPO)     # direct `python tools/check_...py`

from tools.slate_lint import legacy as _legacy  # noqa: E402

#: module path -> instrument_driver op names that must stay decorated
#: (module-level so test fixtures can monkeypatch it; the live-tree
#: truth is tools/slate_lint/legacy.py)
REQUIRED = dict(_legacy.REQUIRED)

KERNELS_PATH = _legacy.KERNELS_PATH
TUNE_CACHE_PATH = _legacy.TUNE_CACHE_PATH
RESIL_GUARD_PATH = _legacy.RESIL_GUARD_PATH
RESIL_FROZEN_ROWS = _legacy.RESIL_FROZEN_ROWS
SHARD_OOC_PATH = _legacy.SHARD_OOC_PATH
SHARD_WAIT_SPAN = _legacy.SHARD_WAIT_SPAN
SHARD_WAIT_COUNTER = _legacy.SHARD_WAIT_COUNTER
SHARD_LOOKAHEAD_ROW = _legacy.SHARD_LOOKAHEAD_ROW
PRECISION_DRIVERS = dict(_legacy.PRECISION_DRIVERS)
CAST_COUNTER_PATH = _legacy.CAST_COUNTER_PATH
CAST_COUNTERS = _legacy.CAST_COUNTERS
REFINE_SPAN_PATH = _legacy.REFINE_SPAN_PATH
REFINE_SPAN = _legacy.REFINE_SPAN
PRECISION_ROW = _legacy.PRECISION_ROW

_decorated_ops = _legacy._decorated_ops


def check_kernel_registry(repo: str = REPO) -> list:
    """Rule 3 (-> SL103): see tools/slate_lint/legacy.py."""
    return _legacy.check_kernel_registry(repo)


def check_resil_contract(repo: str = REPO) -> list:
    """Rule 4 (-> SL104): see tools/slate_lint/legacy.py."""
    return _legacy.check_resil_contract(repo)


def check_shard_lookahead(repo: str = REPO) -> list:
    """Rule 5 (-> SL105): see tools/slate_lint/legacy.py."""
    return _legacy.check_shard_lookahead(repo)


def check_precision_contract(repo: str = REPO) -> list:
    """Rule 6 (-> SL106): see tools/slate_lint/legacy.py. Reads this
    module's PRECISION_DRIVERS so monkeypatched maps take effect."""
    return _legacy.check_precision_contract(
        repo, precision_drivers=PRECISION_DRIVERS)


def check(repo: str = REPO) -> list:
    """All six legacy rules in the historical order, reading this
    module's REQUIRED/PRECISION_DRIVERS (monkeypatch-compatible)."""
    problems = _legacy.check_required(repo, required=REQUIRED)
    problems.extend(check_kernel_registry(repo))
    problems.extend(check_resil_contract(repo))
    problems.extend(check_shard_lookahead(repo))
    problems.extend(check_precision_contract(repo))
    return problems


def main() -> int:
    print("check_instrumented.py is a back-compat shim; prefer "
          "`python -m tools.slate_lint` (analyzers SL101-SL106 are "
          "these rules; SL2xx-SL5xx are new)", file=sys.stderr)
    problems = check()
    for p in problems:
        print("check_instrumented: %s" % p)
    if problems:
        return 1
    print("check_instrumented: ok (%d modules)" % len(REQUIRED))
    return 0


if __name__ == "__main__":
    sys.exit(main())
