"""Communication-avoiding kernels and method routing (reference
getrf_tntpiv tournament LU + ttqrt tree QR; method.hh variants)."""
import sys, pathlib; sys.path.insert(0, str(pathlib.Path(__file__).resolve().parents[1]))  # noqa
import numpy as np
import slate_tpu as st
from slate_tpu.core.methods import MethodGels, MethodLU
from slate_tpu.core.options import Option

rng = np.random.default_rng(1)

# CALU: tournament pivot selection instead of per-column argmax
n = 384
a = rng.standard_normal((n, n)).astype(np.float32) \
    + 0.1 * n * np.eye(n, dtype=np.float32)
b = rng.standard_normal((n, 2)).astype(np.float32)
F, X = st.gesv(st.Matrix(a, mb=64), st.TiledMatrix.from_dense(b, 64),
               {Option.MethodLU: MethodLU.CALU})
r = np.abs(a @ X.to_numpy() - b).max()
print(f"CALU gesv resid {r:.2e}")
assert r < 1e-2

# TSQR: tree QR for a very tall-skinny least squares problem
m, k = 4096, 24
t = rng.standard_normal((m, k)).astype(np.float32)
c = rng.standard_normal((m, 1)).astype(np.float32)
X2 = st.gels(st.Matrix(t, mb=256), st.TiledMatrix.from_dense(c, 256),
             {Option.MethodGels: MethodGels.TSQR})
x_ref = np.linalg.lstsq(t, c, rcond=None)[0]
err = np.abs(X2.to_numpy()[:k] - x_ref).max()
print(f"TSQR gels vs lstsq {err:.2e}")
assert err < 1e-4

# phase timers (reference timers map)
from slate_tpu.utils import Timers
tm = Timers()
st.posv(st.HermitianMatrix(st.Uplo.Lower,
                           a @ a.T / n + 4 * np.eye(n, dtype=np.float32),
                           mb=64),
        st.TiledMatrix.from_dense(b, 64), {Option.Timers: tm})
print("phase timers:", tm)
