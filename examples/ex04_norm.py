"""Norms (reference ex04_norm.cc)."""
import sys, pathlib; sys.path.insert(0, str(pathlib.Path(__file__).resolve().parents[1]))  # noqa
import numpy as np
import slate_tpu as st
from slate_tpu import Norm

a = np.random.default_rng(0).standard_normal((64, 32))
A = st.Matrix(a, mb=16)
for nrm, ref in [(Norm.One, np.abs(a).sum(0).max()),
                 (Norm.Inf, np.abs(a).sum(1).max()),
                 (Norm.Fro, np.linalg.norm(a)),
                 (Norm.Max, np.abs(a).max())]:
    v = float(st.norm(nrm, A))
    assert np.isclose(v, ref), (nrm, v, ref)
    print(f"{nrm.name:4s} norm = {v:.4f}")
