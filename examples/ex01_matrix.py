"""Creating tiled matrices (reference examples/ex01_matrix.cc)."""
import sys, pathlib; sys.path.insert(0, str(pathlib.Path(__file__).resolve().parents[1]))  # noqa
import numpy as np
import slate_tpu as st

A = st.Matrix(np.arange(12.0).reshape(4, 3), mb=2)
print("A:", A.shape, "tiles", A.mt, "x", A.nt)
Z = st.TiledMatrix.zeros(100, 50, 32, dtype=np.float32)
print("Z:", Z.shape, Z.dtype)
H = st.HermitianMatrix(st.Uplo.Lower, np.eye(6), mb=2)
print("H uplo:", H.uplo.name)
assert A.tileMb(1) == 2 and A.tileNb(1) == 1
