"""Band solvers exploiting band structure (reference pbtrf/gbtrf/tbsm;
windowed O(n*kd^2) algorithms, linalg/band.py)."""
import sys, pathlib; sys.path.insert(0, str(pathlib.Path(__file__).resolve().parents[1]))  # noqa
import numpy as np
import slate_tpu as st

rng = np.random.default_rng(0)
n, kd, nb = 512, 8, 32

# SPD band: pbsv runs the windowed band Cholesky + band solves
a = rng.standard_normal((n, n)).astype(np.float32)
band = np.triu(np.tril(a + a.T, kd), -kd) \
    + 30 * np.eye(n, dtype=np.float32)
A = st.HermitianBandMatrix(st.Uplo.Lower, kd, band, mb=nb)
b = rng.standard_normal((n, 3)).astype(np.float32)
L, X = st.pbsv(A, st.TiledMatrix.from_dense(b, nb))
r = np.abs(band @ X.to_numpy() - b).max()
print(f"pbsv n={n} kd={kd} resid {r:.2e}")
assert r < 1e-3
# the factor stays within the band
assert np.allclose(np.tril(L.to_numpy(), -(kd + 1)), 0)

# general band LU: LAPACK gbtrf pivot convention (fill-in to kl+ku,
# block-local swaps replayed by gbtrs)
kl, ku = 5, 3
g = np.triu(np.tril(rng.standard_normal((n, n)).astype(np.float32),
                    kl), -ku).T + 20 * np.eye(n, dtype=np.float32)
F, Y = st.gbsv(st.BandMatrix(kl, ku, g, mb=nb),
               st.TiledMatrix.from_dense(b, nb))
r = np.abs(g @ Y.to_numpy() - b).max()
print(f"gbsv n={n} kl={kl} ku={ku} resid {r:.2e} (band path: {F.band})")
assert r < 1e-3 and F.band
