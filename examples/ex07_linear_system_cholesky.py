"""Cholesky solve — the north-star config (reference
ex07_linear_system_cholesky.cc)."""
import sys, pathlib; sys.path.insert(0, str(pathlib.Path(__file__).resolve().parents[1]))  # noqa
import numpy as np
import slate_tpu as st

n = 256
rng = np.random.default_rng(0)
x = rng.standard_normal((n, n)).astype(np.float32)
a = x @ x.T / n + 4 * np.eye(n, dtype=np.float32)
A = st.HermitianMatrix(st.Uplo.Lower, a, mb=64)
b = rng.standard_normal((n, 4)).astype(np.float32)
L, X = st.posv(A, st.Matrix(b, mb=64))
r = np.linalg.norm(a @ X.to_numpy() - b) / np.linalg.norm(b)
print(f"posv resid {r:.2e}")
assert r < 1e-4
Ainv = st.potri(L)
assert np.abs(Ainv.to_numpy() @ a - np.eye(n)).max() < 1e-2
