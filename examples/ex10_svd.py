"""SVD (reference ex10_svd.cc)."""
import sys, pathlib; sys.path.insert(0, str(pathlib.Path(__file__).resolve().parents[1]))  # noqa
import numpy as np
import slate_tpu as st

m, n = 192, 128
rng = np.random.default_rng(0)
a = rng.standard_normal((m, n)).astype(np.float32)
s, U, Vh = st.svd(st.Matrix(a, mb=64))
rec = (U.to_numpy() * np.asarray(s)[None, :]) @ Vh.to_numpy()
print("svd recon err:", np.abs(rec - a).max())
assert np.abs(rec - a).max() < 1e-3
vals = st.svd_vals(st.Matrix(a, mb=64))
assert np.allclose(np.asarray(vals), np.asarray(s), atol=1e-3)
