"""Symmetric-indefinite Aasen solve (reference
ex08_linear_system_indefinite.cc)."""
import sys, pathlib; sys.path.insert(0, str(pathlib.Path(__file__).resolve().parents[1]))  # noqa
import numpy as np
import slate_tpu as st

n = 128
rng = np.random.default_rng(0)
a = rng.standard_normal((n, n)).astype(np.float32)
a = (a + a.T) / 2          # indefinite
A = st.HermitianMatrix(st.Uplo.Lower, a, mb=32)
b = rng.standard_normal((n, 2)).astype(np.float32)
F, X = st.hesv(A, st.Matrix(b, mb=32))
r = np.linalg.norm(a @ X.to_numpy() - b) / np.linalg.norm(b)
print(f"hesv resid {r:.2e}")
assert r < 1e-3
