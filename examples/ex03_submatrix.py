"""sub() / slice() views (reference ex03_submatrix.cc)."""
import sys, pathlib; sys.path.insert(0, str(pathlib.Path(__file__).resolve().parents[1]))  # noqa
import numpy as np
import slate_tpu as st

a = np.random.default_rng(0).standard_normal((8, 8))
A = st.Matrix(a, mb=2)
S = A.sub(1, 2, 1, 2)
assert np.allclose(S.to_numpy(), a[2:6, 2:6])
E = A.slice(1, 4, 3, 6)
assert np.allclose(E.to_numpy(), a[1:5, 3:7])
print("sub/slice ok")
