"""Distributed solve over a device mesh (reference
ex13_non_uniform_block_size.cc's role of showing distribution control;
TPU-native: a p x q mesh with sharded matrices)."""
import sys, pathlib; sys.path.insert(0, str(pathlib.Path(__file__).resolve().parents[1]))  # noqa
import dataclasses
import numpy as np
import jax
import slate_tpu as st

grid = st.make_grid()          # all available devices, near-square
print(f"grid: {grid.p} x {grid.q} over {grid.nprocs} device(s)")
n, nb = 256, 32
rng = np.random.default_rng(0)
x = rng.standard_normal((n, n)).astype(np.float32)
a = x @ x.T / n + 4 * np.eye(n, dtype=np.float32)
A = st.HermitianMatrix(st.Uplo.Lower, a, mb=nb)
A = dataclasses.replace(A, data=jax.device_put(A.data,
                                               grid.matrix_sharding()))
b = rng.standard_normal((n, 4)).astype(np.float32)
B = st.Matrix(b, mb=nb)
with grid.mesh:
    L, X = jax.jit(st.posv)(A, B)
r = np.linalg.norm(a @ X.to_numpy() - b) / np.linalg.norm(b)
print(f"distributed posv resid {r:.2e}")
assert r < 1e-4
# tile->rank map parity (reference func.hh)
f = grid.tile_rank_func()
print("tile (0,0) -> rank", f((0, 0)), "; tile (1,2) -> rank", f((1, 2)))
