"""LU solves incl. mixed precision (reference ex06_linear_system_lu.cc)."""
import sys, pathlib; sys.path.insert(0, str(pathlib.Path(__file__).resolve().parents[1]))  # noqa
import numpy as np
import slate_tpu as st

n = 256
rng = np.random.default_rng(0)
a = rng.standard_normal((n, n)).astype(np.float32) \
    + 0.3 * n * np.eye(n, dtype=np.float32)
b = rng.standard_normal((n, 4)).astype(np.float32)
F, X = st.gesv(st.Matrix(a, mb=64), st.Matrix(b, mb=64))
r = np.linalg.norm(a @ X.to_numpy() - b) / np.linalg.norm(b)
print(f"gesv resid {r:.2e}")
assert r < 1e-4
F2, X2, iters = st.gesv_mixed(st.Matrix(a, mb=64), st.Matrix(b, mb=64))
print(f"gesv_mixed ({F2.LU.dtype} factor) converged in {int(iters)} iters")
_, X3 = st.gesv_rbt(st.Matrix(a, mb=64), st.Matrix(b, mb=64))
assert np.linalg.norm(a @ X3.to_numpy() - b) / np.linalg.norm(b) < 1e-3
