"""Hermitian eigensolver (reference ex11_hermitian_eig.cc)."""
import sys, pathlib; sys.path.insert(0, str(pathlib.Path(__file__).resolve().parents[1]))  # noqa
import numpy as np
import slate_tpu as st

n = 128
rng = np.random.default_rng(0)
a = rng.standard_normal((n, n)).astype(np.float32)
a = (a + a.T) / 2
A = st.HermitianMatrix(st.Uplo.Lower, a, mb=32)
w, V = st.heev(A)
v = V.to_numpy()
err = np.abs(a @ v - v * np.asarray(w)[None, :]).max()
print("heev resid:", err)
assert err < 1e-3
