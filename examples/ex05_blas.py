"""BLAS-3 (reference ex05_blas.cc: gemm n=2048 nb=256 config)."""
import sys, pathlib; sys.path.insert(0, str(pathlib.Path(__file__).resolve().parents[1]))  # noqa
import numpy as np
import slate_tpu as st
from slate_tpu import Side, Uplo

n, nb = 512, 128     # scaled-down smoke config of ex05's 2048/256
rng = np.random.default_rng(0)
a = rng.standard_normal((n, n)).astype(np.float32)
b = rng.standard_normal((n, n)).astype(np.float32)
C = st.gemm(1.0, st.Matrix(a, mb=nb), st.Matrix(b, mb=nb),
            0.0, st.Matrix(np.zeros_like(a), mb=nb))
assert np.allclose(C.to_numpy(), a @ b, atol=1e-2)
T = st.TriangularMatrix(Uplo.Lower, a + n * np.eye(n, dtype=np.float32),
                        mb=nb)
X = st.trsm(Side.Left, 1.0, T, st.Matrix(b, mb=nb))
print("gemm/trsm ok")
