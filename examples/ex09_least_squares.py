"""Tall-skinny least squares (reference ex09_least_squares.cc)."""
import sys, pathlib; sys.path.insert(0, str(pathlib.Path(__file__).resolve().parents[1]))  # noqa
import numpy as np
import slate_tpu as st

m, n = 1024, 64
rng = np.random.default_rng(0)
a = rng.standard_normal((m, n)).astype(np.float32)
b = rng.standard_normal((m, 2)).astype(np.float32)
X = st.gels(st.Matrix(a, mb=64), st.Matrix(b, mb=64))
x = X.to_numpy()[:n]
xnp, *_ = np.linalg.lstsq(a, b, rcond=None)
assert np.allclose(x, xnp, atol=1e-2)
print("gels (router) ok; QR vs CholQR:")
x1 = st.gels_qr(st.Matrix(a, mb=64), st.Matrix(b, mb=64)).to_numpy()[:n]
x2 = st.gels_cholqr(st.Matrix(a, mb=64),
                    st.Matrix(b, mb=64)).to_numpy()[:n]
print("  max diff:", np.abs(x1 - x2).max())
