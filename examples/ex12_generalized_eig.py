"""Generalized Hermitian eigenproblem (reference ex12)."""
import sys, pathlib; sys.path.insert(0, str(pathlib.Path(__file__).resolve().parents[1]))  # noqa
import numpy as np
import slate_tpu as st

n = 96
rng = np.random.default_rng(0)
a = rng.standard_normal((n, n)); a = ((a + a.T) / 2).astype(np.float64)
bm = rng.standard_normal((n, n))
b = (bm @ bm.T + n * np.eye(n)).astype(np.float64)
A = st.HermitianMatrix(st.Uplo.Lower, a, mb=32)
B = st.HermitianMatrix(st.Uplo.Lower, b, mb=32)
w, V = st.hegv(1, A, B)
v = V.to_numpy()
err = np.abs(a @ v - b @ v * np.asarray(w)[None, :]).max()
print("hegv resid:", err)
assert err < 1e-6
