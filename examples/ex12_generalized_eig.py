"""Generalized Hermitian eigenproblem (reference ex12)."""
import sys, pathlib; sys.path.insert(0, str(pathlib.Path(__file__).resolve().parents[1]))  # noqa
import numpy as np
import slate_tpu as st

# f32: the examples run on the TPU chip, which has no native f64 path
# (f64 inputs would be silently downcast — TiledMatrix warns; enable
# jax x64 on a CPU backend for double-precision runs)
n = 96
rng = np.random.default_rng(0)
a = rng.standard_normal((n, n)); a = ((a + a.T) / 2).astype(np.float32)
bm = rng.standard_normal((n, n))
b = (bm @ bm.T + n * np.eye(n)).astype(np.float32)
A = st.HermitianMatrix(st.Uplo.Lower, a, mb=32)
B = st.HermitianMatrix(st.Uplo.Lower, b, mb=32)
w, V = st.hegv(1, A, B)
v = V.to_numpy()
err = np.abs(a @ v - b @ v * np.asarray(w)[None, :]).max()
scale = np.abs(a).max() + np.abs(w).max() * np.abs(b).max()
print("hegv resid:", err, "scale:", scale)
assert err < 2e-4 * scale   # ~n * eps_f32 * ||problem||
