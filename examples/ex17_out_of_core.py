"""Out-of-HBM streaming drivers (linalg/ooc.py): the matrix lives in
host memory and streams through the accelerator one column panel at a
time — the huge-n regime where n^2 exceeds device memory (SURVEY
§2.3.8; the reference streams remote tiles through per-device
workspace, potrf.cc:179-192)."""
import sys, pathlib; sys.path.insert(0, str(pathlib.Path(__file__).resolve().parents[1]))  # noqa
import numpy as np
from slate_tpu.linalg.ooc import gemm_ooc, potrf_ooc

rng = np.random.default_rng(0)

# out-of-core Cholesky: panels much smaller than the matrix, so the
# left-looking schedule revisits every prior panel (the streamed path)
n = 768
x = rng.standard_normal((n, n)).astype(np.float32)
a = x @ x.T / n + 4.0 * np.eye(n, dtype=np.float32)
L = potrf_ooc(a, panel_cols=128)
r = np.abs(a - L @ L.T).max() / np.abs(a).max()
print(f"potrf_ooc n={n} panel=128 rel resid {r:.2e}")
assert r < 1e-5
assert np.allclose(L, np.tril(L))

# streaming gemm: A and C move in row panels, B stays resident;
# beta=0 follows BLAS (C never read)
m, k, p = 1000, 256, 192
A = rng.standard_normal((m, k)).astype(np.float32)
B = rng.standard_normal((k, p)).astype(np.float32)
C = np.empty((m, p), np.float32)            # uninitialized is legal
got = gemm_ooc(1.0, A, B, 0.0, C, row_panel=256)
err = np.abs(got - A @ B).max()
print(f"gemm_ooc {m}x{k}x{p} beta=0 err {err:.2e}")
assert err < 1e-2

print("out-of-core streaming ok")
