"""Out-of-HBM streaming drivers (linalg/ooc.py): the matrix lives in
host memory and streams through the accelerator one column panel at a
time — the huge-n regime where n^2 exceeds device memory (SURVEY
§2.3.8; the reference streams remote tiles through per-device
workspace, potrf.cc:179-192). The streaming engine (linalg/stream.py)
adds an HBM panel-residency cache + async prefetch/writeback; budget
0 (the default) is the plain uncached stream, a byte budget turns
revisit uploads into cache hits (demonstrated at the end)."""
import sys, pathlib; sys.path.insert(0, str(pathlib.Path(__file__).resolve().parents[1]))  # noqa
import numpy as np
from slate_tpu.linalg.ooc import (gels_ooc, gemm_ooc, gesv_ooc,
                                  posv_ooc, potrf_ooc)

rng = np.random.default_rng(0)

# out-of-core Cholesky: panels much smaller than the matrix, so the
# left-looking schedule revisits every prior panel (the streamed path)
n = 768
x = rng.standard_normal((n, n)).astype(np.float32)
a = x @ x.T / n + 4.0 * np.eye(n, dtype=np.float32)
L = potrf_ooc(a, panel_cols=128)
r = np.abs(a - L @ L.T).max() / np.abs(a).max()
print(f"potrf_ooc n={n} panel=128 rel resid {r:.2e}")
assert r < 1e-5
assert np.allclose(L, np.tril(L))

# streamed Cholesky solve: each factor panel passes through the chip
# twice (non-unit forward sweep, conjugate-transposed backward sweep)
bs = rng.standard_normal((n, 2)).astype(np.float32)
_, xs = posv_ooc(a, bs, panel_cols=128)
rs = np.abs(a @ xs - bs).max() / np.abs(bs).max()
print(f"posv_ooc  n={n} panel=128 rel resid {rs:.2e}")
assert rs < 1e-4

# out-of-core LU solve: left-looking streamed panels with partial
# pivoting confined to the resident panel (pivot sequence identical
# to in-core getrf), host-side row fixups on the written factor
ag = (rng.standard_normal((n, n)) + 0.1 * n * np.eye(n)).astype(np.float32)
bg = rng.standard_normal((n, 3)).astype(np.float32)
_, xg = gesv_ooc(ag, bg, panel_cols=128)
rg = np.abs(ag @ xg - bg).max()
print(f"gesv_ooc  n={n} panel=128 max resid {rg:.2e}")
assert rg < 1e-4                 # f32 on chip (TPU has no native f64)

# out-of-core least squares: streamed Householder QR (compact-WY
# visits), Q^H b by reflector-panel stream, R back-substitution
mq, nq = 1500, 384
aq = rng.standard_normal((mq, nq)).astype(np.float32)
bq = rng.standard_normal((mq, 2)).astype(np.float32)
_, xq = gels_ooc(aq, bq, panel_cols=128)
ref, *_ = np.linalg.lstsq(aq.astype(np.float64),
                          bq.astype(np.float64), rcond=None)
print(f"gels_ooc  {mq}x{nq} panel=128 vs lstsq "
      f"{np.abs(xq - ref).max():.2e}")
assert np.abs(xq - ref).max() < 1e-3      # f32 factorization on chip

# streaming gemm: A and C move in row panels, B stays resident;
# beta=0 follows BLAS (C never read)
m, k, p = 1000, 256, 192
A = rng.standard_normal((m, k)).astype(np.float32)
B = rng.standard_normal((k, p)).astype(np.float32)
C = np.empty((m, p), np.float32)            # uninitialized is legal
got = gemm_ooc(1.0, A, B, 0.0, C, row_panel=256)
err = np.abs(got - A @ B).max()
print(f"gemm_ooc {m}x{k}x{p} beta=0 err {err:.2e}")
assert err < 1e-2

# panel-residency cache: give the engine a budget (here: six full
# panels) and the left-looking revisits are served from device
# memory — bit-identical result, a fraction of the H2D traffic
from slate_tpu.linalg import stream                        # noqa: E402
budget = 6 * n * 128 * a.itemsize
Lc = potrf_ooc(a, panel_cols=128, cache_budget_bytes=budget)
s = stream.last_stats()
assert np.array_equal(L, Lc)            # cache-on == cache-off, exactly
print(f"potrf_ooc cached: hit rate {s['hit_rate']:.0%} "
      f"({s['hits']} hits / {s['misses']} misses, "
      f"{s['evictions']} evictions), "
      f"served {s['served_bytes'] / 1e6:.1f} MB from HBM")
assert s["hits"] > 0

print("out-of-core streaming ok")
