"""Non-uniform tile sizes (reference ex13_non_uniform_block_size.cc,
BaseMatrix.hh:80-101 per-index tileMb/tileNb lambdas).

On TPU the compute layout stays one dense array — the boundaries are
static indexing metadata — so non-uniform tiling costs nothing at trace
time; `uniform()` bridges into the factorization drivers."""
import sys, pathlib; sys.path.insert(0, str(pathlib.Path(__file__).resolve().parents[1]))  # noqa
import numpy as np
import slate_tpu as st
from slate_tpu import TiledMatrix

rng = np.random.default_rng(0)
n = 24
a = rng.standard_normal((n, n)).astype(np.float32)

# custom per-index tile sizes: a small first block then wide blocks
# (the reference example's use case: boundary layers / domain edges)
sizes = [4, 8, 8, 4]
A = TiledMatrix.from_func(a, sizes)
assert A.mt == A.nt == 4
assert [A.tileMb(i) for i in range(A.mt)] == sizes
assert np.allclose(A.tile(1, 2), a[4:12, 12:20])

# lambda form (func.uniform_blocksize is the uniform special case)
from slate_tpu.core.func import uniform_blocksize
B = TiledMatrix.from_func(a, uniform_blocksize(n, 7))
assert [B.tileMb(i) for i in range(B.mt)] == [7, 7, 7, 3]

# sub() keeps the non-uniform structure, re-based
S = A.sub(1, 2, 1, 2)
assert np.allclose(S.to_numpy(), a[4:20, 4:20])
assert [S.tileMb(i) for i in range(S.mt)] == [8, 8]

# gemm consumes non-uniform operands directly
b = rng.standard_normal((n, n)).astype(np.float32)
C = st.gemm(1.0, A, TiledMatrix.from_func(b, sizes), 0.0,
            TiledMatrix.from_func(np.zeros_like(a), sizes))
assert np.allclose(C.to_numpy(), a @ b, atol=1e-4)

# factorizations re-tile uniformly at entry
F = st.getrf(A.uniform())
x = st.getrs(F, st.Matrix(b[:, :2], mb=8))
assert np.allclose(a @ x.to_numpy(), b[:, :2], atol=1e-3)
print("non-uniform tiles ok")
