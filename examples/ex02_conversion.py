"""Transpose-by-flag and dtype conversion (reference ex02)."""
import sys, pathlib; sys.path.insert(0, str(pathlib.Path(__file__).resolve().parents[1]))  # noqa
import numpy as np
import slate_tpu as st

a = np.random.default_rng(0).standard_normal((6, 4))
A = st.Matrix(a, mb=2)
At = A.T
assert At.shape == (4, 6)
assert np.allclose(At.to_numpy(), a.T)
B32 = st.copy(A, st.TiledMatrix.zeros(6, 4, 2, dtype=np.float32))
print("converted:", B32.dtype)
