#!/usr/bin/env python
"""Run every example as a smoke test (reference examples/run_tests.py,
CTest mpirun role — here: single process, all jax devices)."""

import pathlib
import runpy
import sys

here = pathlib.Path(__file__).parent
sys.path.insert(0, str(here.parent))

# Fail fast on a dead TPU tunnel: backend init hangs forever in C code,
# so probe in a subprocess and fall back to CPU with a loud warning.
from slate_tpu.utils.backend import probe_backend, force_cpu  # noqa: E402

ok, info = probe_backend()
if ok:
    print(f"backend probe ok: {info}")
else:
    print(f"WARNING: ambient backend unavailable ({info}); "
          "falling back to CPU", file=sys.stderr)
    force_cpu()

failed = []
for ex in sorted(here.glob("ex*.py")):
    print(f"=== {ex.name} ===")
    try:
        runpy.run_path(str(ex), run_name="__main__")
    except Exception as e:   # noqa: BLE001
        print(f"FAILED: {e}")
        failed.append(ex.name)
print("\n" + ("All examples passed" if not failed
              else f"FAILED: {failed}"))
sys.exit(1 if failed else 0)
