#!/usr/bin/env python
"""Run every example as a smoke test (reference examples/run_tests.py,
CTest mpirun role — here: single process, all jax devices)."""

import pathlib
import runpy
import sys

here = pathlib.Path(__file__).parent
sys.path.insert(0, str(here.parent))

failed = []
for ex in sorted(here.glob("ex*.py")):
    print(f"=== {ex.name} ===")
    try:
        runpy.run_path(str(ex), run_name="__main__")
    except Exception as e:   # noqa: BLE001
        print(f"FAILED: {e}")
        failed.append(ex.name)
print("\n" + ("All examples passed" if not failed
              else f"FAILED: {failed}"))
sys.exit(1 if failed else 0)
